"""Build and dispatch fused train bursts.

A *train burst* is one replay-staged block of ``n_samples`` gradient steps.
The per-step shape — ``for i in range(n_samples): train_fn(state, batch[i],
...)`` — pays one host→device dispatch round trip per gradient step, and on a
remote-attached accelerator that round trip scales with the donated state's
leaf count (~120 ms measured for the DV3 agent pytree over a tunnel).
:func:`build_train_burst` wraps a single-gradient-step function into a
:class:`TrainProgram` whose ``.burst`` runs the whole block as ONE jitted
``lax.scan`` program: the agent state rides the scan carry (donated, so
optimizer/ensemble state never round-trips), while everything that varies per
step — the staged ``[n_samples, ...]`` batch stack, per-step PRNG keys, and
host-computed scalar schedules such as the target-update ``tau`` cadence —
is scanned over as arrays.

Determinism contract: the burst program's loop bound is a runtime scalar,
so the fused dispatch (count=n) and a sequential per-step loop (n dispatches
of count=1) execute the same while-loop body of the same executable over
the same ``(batch, key, schedule)`` tuples — bitwise identical BY
CONSTRUCTION under fixed seeds (checkpoint state compared;
``tests/test_algos`` holds the per-family proof). Setting
``SHEEPRL_TRAIN_NO_FUSE=1`` makes :func:`run_train_burst` dispatch that
sequential reference loop instead — same staged stack, same key discipline —
which is both the parity-test harness and the per-step side of the
``dv2_train_burst_sps`` bench line.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.obs import get_telemetry, register_train_cost, shape_specs
from sheeprl_tpu.obs import learn as _learn
from sheeprl_tpu.obs.counters import add_train_burst
from sheeprl_tpu.obs.learn import split_probes
from sheeprl_tpu.utils.jax_compat import shard_map


class TrainProgram:
    """One-gradient-step program plus the fused whole-burst variant.

    Callable like the plain step (existing tests/benches and the per-step
    reference loop), with ``.burst`` for the scan-over-samples program the
    train loops dispatch and ``.extras`` (optional) for the burst's extra
    outputs recomputed standalone on the per-step path.
    """

    def __init__(self, step_fn, burst_fn, extras_fn=None):
        self._step = step_fn
        self.burst = burst_fn
        self.extras = extras_fn

    def __call__(self, *args, **kwargs):
        return self._step(*args, **kwargs)

    def lower(self, *args, **kwargs):
        return self._step.lower(*args, **kwargs)


def build_train_burst(
    local_step: Callable,
    fabric,
    *,
    n_scanned: int = 1,
    data_dim: int = 1,
    plan=None,
    metric_mode: str = "last",
    extra_outputs: Optional[Callable] = None,
) -> TrainProgram:
    """Wrap a single-gradient-step function into a :class:`TrainProgram`.

    ``local_step(agent_state, data, *scanned) -> (agent_state, metrics)`` is
    the *pre-shard_map* per-step function: ``data`` is one step's batch with
    the sharded axis at position ``data_dim`` (1 for ``[T, B, ...]`` sequence
    batches, 0 for ``[B, ...]`` transition batches) and ``scanned`` are
    ``n_scanned`` per-step scalars (the PRNG key, then any host-computed
    schedules such as ``tau``). Both compiled variants donate the agent
    state:

    - the step program shards ``data`` over the batch axis and runs one
      gradient step (``shard_map`` on the data mesh, or the GSPMD ``plan``
      path when a sharding plan is provided);
    - the burst program ``burst(state, data_stack, start, count, *scanned)``
      runs gradient steps ``start..start+count-1`` over the stacked
      ``[n_samples, ...]`` batches and scanned arrays as ONE dispatch, the
      state riding the loop carry, and reduces the per-step metrics on
      device per ``metric_mode`` (``"last"`` — what the aggregator consumed
      under the sequential loop — ``"mean"``, or ``"stack"``). ``start`` and
      ``count`` are runtime scalars, so one compiled program serves every
      burst length — and the per-step reference mode (see
      :func:`run_train_burst`) bitwise-matches the fused mode by
      construction.

    ``extra_outputs(state) -> pytree`` appends extra burst outputs computed
    from the final state inside the same program (DV3's packed acting
    vector); the same function is compiled standalone as ``.extras`` so the
    per-step reference path can reproduce it.
    """
    if metric_mode not in ("last", "mean", "stack"):
        raise ValueError(f"metric_mode must be last|mean|stack, got {metric_mode!r}")
    data_axis = fabric.data_axis
    step_data_dims = [None] * int(data_dim) + [data_axis]

    def local_burst(agent_state, data_stack, start, count, *scanned):
        # The loop bound is DYNAMIC (a runtime scalar, not a trace constant):
        # ONE compiled program serves both the fused burst (start=0, count=n)
        # and the per-step reference loop (n dispatches of count=1). That is
        # what makes the two modes bitwise identical BY CONSTRUCTION — two
        # differently-jitted programs of the same math may legally differ in
        # the last ulp (XLA fuses a scan body, a standalone step, and a
        # trip-count-1 loop differently; measured ~1e-9 drift on CPU), but
        # here every gradient step executes the same while-loop body of the
        # same executable. Same trick as the rollout engine's dynamic-length
        # acting burst (envs/rollout/burst.py).
        def at(i, tree):
            return jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(x, i, 0, keepdims=False), tree
            )

        # abstract-eval one step to build the metric carry structure; the
        # learn-probe keys (obs/learn, "learn/" prefix) are split out and
        # ALWAYS stack-accumulated — the sentinel grades every per-step
        # sample, so "last"/"mean" reductions would hide exactly the
        # excursions it exists to catch
        metric_shapes = jax.eval_shape(
            local_step, agent_state, at(0, data_stack), *at(0, scanned)
        )[1]
        metric_shapes, learn_shapes = split_probes(metric_shapes)
        n_stack = (
            int(np.shape(jax.tree_util.tree_leaves(scanned[0])[0])[0])
            if (metric_mode == "stack" or learn_shapes)
            else 0
        )
        if metric_mode == "stack":
            init_metrics = jax.tree_util.tree_map(
                lambda s: jnp.zeros((n_stack,) + tuple(s.shape), s.dtype), metric_shapes
            )
        else:
            init_metrics = jax.tree_util.tree_map(
                lambda s: jnp.zeros(tuple(s.shape), s.dtype), metric_shapes
            )
        init_learn = (
            {
                k: jnp.zeros((n_stack,) + tuple(s.shape), s.dtype)
                for k, s in learn_shapes.items()
            }
            if learn_shapes
            else {}
        )

        def body(i, carry):
            state, metrics, learn = carry
            new_state, m = local_step(state, at(i, data_stack), *at(i, scanned))
            m, lm = split_probes(m)
            if lm:
                learn = {
                    k: jax.lax.dynamic_update_index_in_dim(learn[k], lm[k], i, 0)
                    for k in learn
                }
            if metric_mode == "last":
                metrics = m
            elif metric_mode == "mean":
                metrics = jax.tree_util.tree_map(jnp.add, metrics, m)
            else:
                metrics = jax.tree_util.tree_map(
                    lambda buf, v: jax.lax.dynamic_update_index_in_dim(buf, v, i, 0),
                    metrics,
                    m,
                )
            return (new_state, metrics, learn)

        state, metrics, learn = jax.lax.fori_loop(
            start, start + count, body, (agent_state, init_metrics, init_learn)
        )
        if metric_mode == "mean":
            denom = jnp.maximum(count, 1)
            metrics = jax.tree_util.tree_map(
                lambda x: x / denom.astype(x.dtype), metrics
            )
        if learn:
            metrics = {**metrics, **learn}
        outs = (state, metrics)
        if extra_outputs is not None:
            outs = outs + (extra_outputs(state),)
        return outs

    n_extra = 1 if extra_outputs is not None else 0
    if plan is None:
        step_fn = jax.jit(
            shard_map(
                local_step,
                mesh=fabric.mesh,
                in_specs=(P(), P(*step_data_dims)) + (P(),) * n_scanned,
                out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )
        burst_fn = jax.jit(
            shard_map(
                local_burst,
                mesh=fabric.mesh,
                in_specs=(P(), P(None, *step_data_dims), P(), P()) + (P(),) * n_scanned,
                out_specs=(P(), P()) + (P(),) * n_extra,
                check_vma=False,
            ),
            donate_argnums=(0,),
        )
        extras_fn = jax.jit(extra_outputs) if extra_outputs is not None else None
    else:
        state_sh = plan.shardings()
        rep = fabric.replicated
        step_fn = jax.jit(
            local_step,
            in_shardings=(state_sh, fabric.sharding(*step_data_dims)) + (rep,) * n_scanned,
            out_shardings=(state_sh, rep),
            donate_argnums=(0,),
        )
        # the extra outputs (e.g. the packed acting vector) leave replicated:
        # the player consumes them whole, so any all-gather happens once here
        # instead of at every acting dispatch
        burst_fn = jax.jit(
            local_burst,
            in_shardings=(state_sh, fabric.sharding(None, *step_data_dims), rep, rep)
            + (rep,) * n_scanned,
            out_shardings=(state_sh, rep) + (rep,) * n_extra,
            donate_argnums=(0,),
        )
        extras_fn = (
            jax.jit(extra_outputs, in_shardings=(state_sh,), out_shardings=rep)
            if extra_outputs is not None
            else None
        )
    return TrainProgram(step_fn, burst_fn, extras_fn)


def tau_schedule(
    n: int, start: int, every: int, *, tau: float = 1.0, first_hard: bool = True
) -> np.ndarray:
    """Host-side target-update schedule for gradient steps ``start..start+n-1``.

    Step ``g`` updates the target network (``tau`` on the cadence, 0.0 off
    it): hard-copy families (DV2) pass ``tau=1.0, first_hard=False``;
    EMA families (DV3) pass their soft ``tau`` with ``first_hard=True`` so
    the run's very first gradient step hard-copies regardless. A pretrain
    catch-up burst at ``learning_starts`` is just a large ``n`` — the
    cadence falls out of the same arithmetic.
    """
    g = int(start) + np.arange(int(n), dtype=np.int64)
    out = np.where(g % max(int(every), 1) == 0, np.float32(tau), np.float32(0.0))
    if first_hard:
        out = np.where(g == 0, np.float32(1.0), out)
    return out.astype(np.float32)


def metric_fetch_gate(
    cfg,
    aggregator,
    *,
    policy_step: int,
    last_log: int,
    train_step: int,
    update: int,
    num_updates: int,
    policy_steps_per_update: int,
    world_size: int,
) -> bool:
    """Should THIS burst's metrics be pulled to host? (DV3's gate, shared.)

    On a bandwidth-limited host link every blocking device→host metric fetch
    costs a round trip; ``metric.fetch_train_metrics_every=k`` samples the
    train metrics every k-th burst (always on the last burst before a log
    boundary), 1 = every burst (default), 0 = log boundaries only. Log
    boundaries are crossed by policy steps, not bursts, so look ahead one
    real burst period (bursts recur every
    ``max(train_every // policy_steps_per_update, 1)`` updates, NOT every
    ``train_every`` policy steps when the two don't divide): if the
    threshold falls before the next burst, this is the burst whose metrics
    that log will see.
    """
    if aggregator is None or aggregator.disabled:
        return False
    burst_updates = max(int(cfg.algo.train_every) // int(policy_steps_per_update), 1)
    burst_period = burst_updates * int(policy_steps_per_update)
    will_log = cfg.metric.log_level > 0 and (
        policy_step - last_log + burst_period >= cfg.metric.log_every
        # the run's last burst feeds the final update==num_updates log even
        # when that update itself is not a burst
        or update + burst_updates > num_updates
    )
    fetch_every = int(cfg.metric.get("fetch_train_metrics_every", 1))
    return will_log or (fetch_every > 0 and (train_step // world_size) % fetch_every == 0)


def fused_enabled() -> bool:
    """Fused dispatch unless ``SHEEPRL_TRAIN_NO_FUSE`` opts into the
    per-step reference loop (parity tests, bench per-step side)."""
    return os.environ.get("SHEEPRL_TRAIN_NO_FUSE", "0") in ("", "0")


def run_train_burst(
    train_fn: TrainProgram,
    agent_state: Any,
    data_stack: Any,
    scanned: Sequence[Any],
    *,
    world_size: int = 1,
    fetch_metrics: bool = True,
    pacing_metric: str = "Loss/world_model_loss",
    probe=None,
) -> Tuple[Any, Optional[Any], Tuple[Any, ...]]:
    """Dispatch one training burst and account for it.

    ``scanned`` are the per-step arrays (keys first, then schedules), each
    ``[n_samples, ...]``. Returns ``(agent_state, metrics_or_None, extras)``:
    metrics are device_get-fetched only when ``fetch_metrics`` (the
    :func:`metric_fetch_gate` decision); otherwise one scalar is pulled as a
    pacing barrier — unbounded dispatch run-ahead on a remote-attached
    device lets per-call overhead compound (measured: acting latency grows
    without it), while on local devices the wait is the device's own step
    time — and ``None`` is returned.

    The burst is ONE device dispatch; ``register_train_cost`` therefore
    books its AOT cost at ``dispatches_per_step=1`` so MFU accounting stays
    unit-correct, and the ``train_bursts``/``train_dispatches`` counters
    record the dispatch economy the fusion buys. Under
    ``SHEEPRL_TRAIN_NO_FUSE=1`` the same burst runs as the sequential
    per-step reference loop (``n_samples`` dispatches, identical
    ``(batch, key, schedule)`` tuples → bitwise-identical state).

    ``probe`` (an ``obs.LoopProbe`` or anything with ``.lap(name)``) gets
    ``train_dispatch``/``metric_fetch`` lap marks around the two phases.

    When the step's metrics carry ``learn/`` probe keys (obs/learn), the
    stacked probe subtree is split off before the fetch/pacing logic and fed
    to the installed sentinel — one extra scalar pull per burst at most,
    nothing when probes are off (the keys simply don't exist).
    """
    scanned = tuple(scanned)
    n = int(np.shape(scanned[0])[0])
    telemetry = get_telemetry()
    want_cost = telemetry is not None and telemetry.needs_train_flops()
    if fused_enabled():
        burst_args = (agent_state, data_stack, np.int32(0), np.int32(n)) + scanned
        # specs captured pre-call: the burst donates agent_state
        specs = shape_specs(burst_args) if want_cost else None
        out = train_fn.burst(*burst_args)
        agent_state, metrics = out[0], out[1]
        metrics, learn_dev = split_probes(metrics)
        extras = tuple(out[2:])
        add_train_burst(steps=n, dispatches=1)
        if specs is not None:
            # one AOT cost analysis of the burst program (FLOPs + bytes
            # accessed), registered per train-step UNIT; the documented
            # while-body-once caveat (obs/perf.py) applies as it did to the
            # scan-based DV3 burst this engine generalizes
            register_train_cost(telemetry, train_fn.burst, *specs, world_size=world_size)
    else:
        # the reference loop dispatches the SAME compiled program n times
        # with count=1 — one dispatch per gradient step, every step running
        # the identical while-loop body. The full stacks are passed each
        # time (already committed on device: no re-upload), only start moves.
        specs = None
        metrics = None
        out = None
        learn_rows = []
        for i in range(n):
            step_args = (agent_state, data_stack, np.int32(i), np.int32(1)) + scanned
            if specs is None and want_cost:
                specs = shape_specs(step_args)
            out = train_fn.burst(*step_args)
            agent_state, metrics = out[0], out[1]
            metrics, learn_i = split_probes(metrics)
            if learn_i:
                # each count=1 call writes exactly slot i of its [n] learn
                # buffers; that row is bitwise the fused stack's row i (same
                # executable wrote it)
                learn_rows.append(
                    jax.tree_util.tree_map(
                        lambda x: jax.lax.index_in_dim(x, i, 0, keepdims=False),
                        learn_i,
                    )
                )
        learn_dev = (
            {k: jnp.stack([r[k] for r in learn_rows]) for k in learn_rows[0]}
            if learn_rows
            else None
        )
        extras = tuple(out[2:]) if out is not None else ()
        add_train_burst(steps=n, dispatches=n)
        if specs is not None:
            register_train_cost(
                telemetry,
                train_fn.burst,
                *specs,
                world_size=world_size,
                dispatches_per_step=n,
            )
    if probe is not None:
        probe.lap("train_dispatch")
    # learn-probe feed: at most ONE extra device_get per burst (cadence- and
    # install-gated inside observe_probes; uninstrumented runs see no learn
    # keys at all and pay nothing here)
    _learn.observe_probes(learn_dev)
    if metrics is not None and fetch_metrics:
        metrics = jax.device_get(metrics)
    elif metrics is not None:
        leaf = metrics.get(pacing_metric) if isinstance(metrics, dict) else None
        if leaf is None:
            leaf = jax.tree_util.tree_leaves(metrics)[0]
        np.asarray(leaf)
        metrics = None
    if probe is not None:
        probe.lap("metric_fetch")
    return agent_state, metrics, extras
