"""Shared train-burst engine: one scanned device program per gradient burst.

Promotes DreamerV3's private ``train_fn.burst`` pattern (one ``lax.scan``
dispatch per training burst instead of one dispatch per gradient step) to
framework infrastructure shared by every dreamer-family entrypoint. See
``howto/train_burst.md`` for the burst contract.
"""

from sheeprl_tpu.train.burst import (
    TrainProgram,
    build_train_burst,
    metric_fetch_gate,
    run_train_burst,
    tau_schedule,
)

__all__ = [
    "TrainProgram",
    "build_train_burst",
    "metric_fetch_gate",
    "run_train_burst",
    "tau_schedule",
]
