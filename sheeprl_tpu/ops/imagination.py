"""Fused DreamerV3 imagination rollout — a Pallas TPU kernel (EXPERIMENTAL).

Status (round 2, v5e, S preset bf16): 1.6x over the lax scan standalone,
but net-neutral in the full train step (14.67 vs 14.55 ms) — the d-major
consumer-side permutation (:func:`dmajor_module_params`) removed the
trajectory-transpose overhead, and what remains is the pallas custom-call
scheduling barrier plus the per-step weight pack. ``algo.fused_imagination``
therefore defaults False; the path is correct, numerically pinned by tests,
and kept for bigger-model presets / future Mosaic scheduling improvements.

The imagination phase (reference dreamer_v3.py:231-269) is a closed loop:
``actor → sample action → recurrent cell → transition → sample latent``,
H=15 sequential steps at batch T·B. For discrete actors the rollout is
*gradient-free* (the actor objective is REINFORCE on re-evaluated log-probs,
reference :304-339), so a forward-only kernel can replace the whole
``lax.scan``: every weight stays resident in VMEM across all H steps and
the per-step HBM traffic drops to the pre-drawn sampling noise and the
emitted trajectory.

Design notes:

- **d-major latent layout.** The 32×32 categorical latent is carried flat
  in *d-major* order (flat index = d·S + s) inside the kernel, so the
  per-group softmax/argmax over D becomes elementwise max/sum over D
  contiguous ``[TILE, S]`` lane slices — no in-kernel reshapes, gathers, or
  segment reductions (all Mosaic-unfriendly). :func:`pack_params` permutes
  the affected weight rows/columns once per train step (a cheap gather on
  ~4M params), and the caller transposes the emitted latents back to the
  framework's s-major convention with one XLA transpose.
- **Sampling = add + compare.** Gumbel noise is pre-drawn outside (same
  trick as the lax path since the scan optimizations); a categorical sample
  is ``argmax(log(unimix probs) + g)`` and the one-hot is an equality
  against the running max (gumbel ties have measure zero).
- The grid runs over batch tiles; weights use constant index maps so Mosaic
  keeps them in VMEM across grid steps.

Use :func:`fused_imagination_supported` to gate (TPU, single discrete
action head); the lax fallback lives in the algorithm files. The pure-jax
mirror :func:`rollout_reference` is bit-comparable to the kernel (tests run
it against ``interpret=True`` and against the compiled kernel on TPU).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def dmajor_perm(S: int, D: int) -> np.ndarray:
    """``perm`` such that ``x_dmajor = x_smajor[..., perm]``: element
    ``j = d*S + s`` of the d-major layout is element ``s*D + d`` of the
    framework's s-major layout."""
    j = np.arange(S * D)
    d, s = j // S, j % S
    return s * D + d


def smajor_perm(S: int, D: int) -> np.ndarray:
    """Inverse of :func:`dmajor_perm`."""
    return np.argsort(dmajor_perm(S, D))


def pack_params(
    actor_params: Dict[str, Any],
    rssm_params: Dict[str, Any],
    n_actor_layers: int,
    S: int,
    D: int,
    rec_size: int,
    dtype=jnp.bfloat16,
) -> Dict[str, jnp.ndarray]:
    """Extract + permute the weights the rollout touches into kernel layout.

    Matmul kernels are cast to ``dtype`` (bf16 on TPU); LayerNorm params
    stay f32. Rows that consume the latent and columns that produce it are
    permuted to d-major (see module docstring).
    """
    SD = S * D
    perm = dmajor_perm(S, D)
    p: Dict[str, jnp.ndarray] = {}

    amlp = actor_params["MLP_0"]
    w1 = amlp["Dense_0"]["kernel"]  # [SD + rec, dense]
    p["actor_w1_z"] = w1[:SD][perm].astype(dtype)
    p["actor_w1_h"] = w1[SD:].astype(dtype)
    p["actor_ln1_s"] = amlp["LayerNorm_0"]["scale"]
    p["actor_ln1_b"] = amlp["LayerNorm_0"]["bias"]
    for i in range(1, n_actor_layers):
        p[f"actor_w{i + 1}"] = amlp[f"Dense_{i}"]["kernel"].astype(dtype)
        p[f"actor_ln{i + 1}_s"] = amlp[f"LayerNorm_{i}"]["scale"]
        p[f"actor_ln{i + 1}_b"] = amlp[f"LayerNorm_{i}"]["bias"]
    p["actor_head_w"] = actor_params["head_0"]["kernel"].astype(dtype)
    p["actor_head_b"] = actor_params["head_0"]["bias"]

    rm = rssm_params["recurrent_model"]
    wpre = rm["MLP_0"]["Dense_0"]["kernel"]  # [SD + A, dense]
    p["pre_w_z"] = wpre[:SD][perm].astype(dtype)
    p["pre_w_a"] = wpre[SD:].astype(dtype)
    p["pre_ln_s"] = rm["MLP_0"]["LayerNorm_0"]["scale"]
    p["pre_ln_b"] = rm["MLP_0"]["LayerNorm_0"]["bias"]
    p["gru_w"] = rm["gru"]["Dense_0"]["kernel"].astype(dtype)  # [rec+dense, 3rec]
    p["gru_ln_s"] = rm["gru"]["LayerNorm_0"]["scale"]
    p["gru_ln_b"] = rm["gru"]["LayerNorm_0"]["bias"]

    tm = rssm_params["transition_model"]
    p["trans_w"] = tm["MLP_0"]["Dense_0"]["kernel"].astype(dtype)
    p["trans_ln_s"] = tm["MLP_0"]["LayerNorm_0"]["scale"]
    p["trans_ln_b"] = tm["MLP_0"]["LayerNorm_0"]["bias"]
    p["trans_head_w"] = tm["head"]["kernel"][:, perm].astype(dtype)
    p["trans_head_b"] = tm["head"]["bias"][perm]
    return p


_PACK_ORDER_FIXED = [
    "actor_w1_z", "actor_w1_h", "actor_ln1_s", "actor_ln1_b",
    "actor_head_w", "actor_head_b",
    "pre_w_z", "pre_w_a", "pre_ln_s", "pre_ln_b",
    "gru_w", "gru_ln_s", "gru_ln_b",
    "trans_w", "trans_ln_s", "trans_ln_b", "trans_head_w", "trans_head_b",
]


def _pack_order(n_actor_layers: int):
    extra = []
    for i in range(1, n_actor_layers):
        extra += [f"actor_w{i + 1}", f"actor_ln{i + 1}_s", f"actor_ln{i + 1}_b"]
    return _PACK_ORDER_FIXED[:4] + extra + _PACK_ORDER_FIXED[4:]


def _ln(x, scale, bias, eps=1e-3):
    # matches flax.linen.LayerNorm incl. its fast-variance form
    # (E[x^2] - E[x]^2), so the mirror tracks the module bit-for-bit
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(x * x, axis=-1, keepdims=True) - mu * mu, 0.0)
    return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def _silu(x):
    return x * jax.nn.sigmoid(x)


def _dot(a, b, dtype):
    return jax.lax.dot_general(
        a.astype(dtype), b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _actor_half(p, n_actor_layers, A, unimix, z, h, ga_t, dtype):
    """Actor trunk + head + gumbel-argmax action on (d-major) ``z`` and ``h``."""
    dot = lambda a, b: _dot(a, b, dtype)
    x = _silu(_ln(dot(z, p["actor_w1_z"]) + dot(h, p["actor_w1_h"]),
                  p["actor_ln1_s"], p["actor_ln1_b"]))
    for i in range(1, n_actor_layers):
        x = _silu(_ln(dot(x, p[f"actor_w{i + 1}"]),
                      p[f"actor_ln{i + 1}_s"], p[f"actor_ln{i + 1}_b"]))
    logits_a = dot(x, p["actor_head_w"]) + p["actor_head_b"]
    pa = jax.nn.softmax(logits_a, axis=-1)
    if unimix > 0.0:
        pa = (1.0 - unimix) * pa + unimix / A
    score = jnp.log(pa) + ga_t
    return (score == jnp.max(score, axis=-1, keepdims=True)).astype(jnp.float32)


def _dynamics_half(p, S, D, rec, unimix, z, h, a, gz_t, dtype):
    """Recurrent cell + transition sample on (d-major) ``z``; returns the
    advanced ``(z, h)``."""
    f32 = jnp.float32
    dot = lambda x, w: _dot(x, w, dtype)

    # recurrent cell (pre-MLP + LayerNorm GRU)
    feat = _silu(_ln(dot(z, p["pre_w_z"]) + dot(a, p["pre_w_a"]),
                     p["pre_ln_s"], p["pre_ln_b"]))
    zg = _ln(dot(h, p["gru_w"][:rec]) + dot(feat, p["gru_w"][rec:]),
             p["gru_ln_s"], p["gru_ln_b"])
    reset = jax.nn.sigmoid(zg[:, :rec])
    cand = jnp.tanh(reset * zg[:, rec:2 * rec])
    update = jax.nn.sigmoid(zg[:, 2 * rec:] - 1.0)
    h = update * cand + (1.0 - update) * h

    # transition (prior) trunk + head, then the d-major grouped sample
    y = _silu(_ln(dot(h, p["trans_w"]), p["trans_ln_s"], p["trans_ln_b"]))
    lg = dot(y, p["trans_head_w"]) + p["trans_head_b"]  # [TILE, D*S] d-major

    def dsl(x, d):
        return x[:, d * S:(d + 1) * S]  # static slice (pallas-lowerable)

    m = dsl(lg, 0)
    for d in range(1, D):
        m = jnp.maximum(m, dsl(lg, d))
    zsum = dsl(lg, 0) * 0.0
    for d in range(D):
        zsum = zsum + jnp.exp(dsl(lg, d) - m)
    # per-slice mixed log-prob + gumbel, tracking the group max
    scores = []
    for d in range(D):
        pd = jnp.exp(dsl(lg, d) - m) / zsum
        if unimix > 0.0:
            pd = (1.0 - unimix) * pd + unimix / D
        scores.append(jnp.log(pd) + dsl(gz_t, d))
    gm = scores[0]
    for d in range(1, D):
        gm = jnp.maximum(gm, scores[d])
    z = jnp.concatenate([(sc == gm).astype(f32) for sc in scores], axis=1)
    return z, h


def _step(p, n_actor_layers, S, D, A, rec, unimix, z, h, gz_t, ga_t, dtype):
    """One full rollout step — shared by the pallas kernel body and the
    pure-jax reference, so they cannot diverge."""
    a = _actor_half(p, n_actor_layers, A, unimix, z, h, ga_t, dtype)
    z, h = _dynamics_half(p, S, D, rec, unimix, z, h, a, gz_t, dtype)
    return z, h, a


def _make_kernel(H, S, D, A, rec, n_actor_layers, unimix, dtype):
    from jax.experimental import pallas as pl

    names = _pack_order(n_actor_layers)

    def kernel(z_ref, h_ref, ga_ref, gz_ref, *rest):
        # grid = (batch_tile, t): t iterates fastest; the rollout state for
        # the current batch tile is carried across t in VMEM scratch, and the
        # per-step noise/trajectory blocks stream through small buffers.
        n_w = len(names)
        weight_refs = rest[:n_w]
        lat_ref, act_ref = rest[n_w:n_w + 2]
        z_s, h_s = rest[n_w + 2:]
        p = {k: r[...] for k, r in zip(names, weight_refs)}
        t = pl.program_id(1)

        @pl.when(t == 0)
        def _():
            z_s[...] = z_ref[...].astype(jnp.float32)
            h_s[...] = h_ref[...].astype(jnp.float32)

        a = _actor_half(
            p, n_actor_layers, A, unimix, z_s[...], h_s[...],
            ga_ref[0].astype(jnp.float32), dtype,
        )
        act_ref[0] = a

        # the caller discards the latent advanced past the last action, so
        # the final grid step skips the whole dynamics half
        @pl.when(t + 1 < pl.num_programs(1))
        def _():
            z, h = _dynamics_half(
                p, S, D, rec, unimix, z_s[...], h_s[...], a,
                gz_ref[0].astype(jnp.float32), dtype,
            )
            z_s[...] = z
            h_s[...] = h
            lat_ref[0, :, : S * D] = z
            lat_ref[0, :, S * D:] = h

    return kernel


def dmajor_module_params(mparams: Dict[str, Any], S: int, D: int) -> Dict[str, Any]:
    """Module params whose first dense kernel consumes d-major ``[z_dm, h]``
    latents: ``x_dm @ W' == x_sm @ W`` with ``W'[j] = W[perm[j]]`` on the
    ``S*D`` latent rows (``h`` rows untouched). Lets every consumer of the
    kernel's trajectory run on the emitted d-major layout directly — a few
    ``[S*D, units]`` weight gathers instead of physically transposing the
    ``[H, N, S*D]`` trajectory. The gather is differentiable, so gradients
    land on the original (s-major) parameter layout.

    Expects the DV3 head-module shape ``{"MLP_0": {"Dense_0": {"kernel":
    [S*D + rec, units]}}, ...}`` (actor / critic / reward / continue).
    """
    perm = jnp.asarray(dmajor_perm(S, D))
    SD = S * D
    mlp = mparams["MLP_0"]
    dense = mlp["Dense_0"]
    k = dense["kernel"]
    k_dm = jnp.concatenate([k[:SD][perm], k[SD:]], axis=0)
    return {
        **mparams,
        "MLP_0": {**mlp, "Dense_0": {**dense, "kernel": k_dm}},
    }


def fused_imagination_supported(is_continuous: bool, actions_dim: Sequence[int]) -> bool:
    """Kernel applicability: single discrete action head (the rollout is
    gradient-free only for the REINFORCE/discrete objective)."""
    return (not is_continuous) and len(tuple(actions_dim)) == 1


def rollout_reference(packed, z0_dm, h0, gz_dm, ga, *, H, S, D, A, rec,
                      n_actor_layers, unimix):
    """Pure-jax mirror of the kernel (same math, same d-major layout) —
    ground truth for tests and the non-TPU fallback."""

    dtype = packed["gru_w"].dtype  # matmul dtype follows the packed weights

    assert gz_dm.shape[0] == H and ga.shape[0] == H, (gz_dm.shape, ga.shape, H)

    def step(carry, inp):
        z, h = carry
        gz_t, ga_t = inp
        z, h, a = _step(
            packed, n_actor_layers, S, D, A, rec, unimix,
            z, h, gz_t, ga_t, dtype,
        )
        return (z, h), (jnp.concatenate([z, h], -1), a)

    (_, _), (lat, act) = jax.lax.scan(
        step, (z0_dm.astype(jnp.float32), h0.astype(jnp.float32)), (gz_dm, ga)
    )
    return lat, act


def rollout_pallas(packed, z0_dm, h0, gz_dm, ga, *, H, S, D, A, rec,
                   n_actor_layers, unimix, tile=64, interpret=False):
    """Run the fused rollout. Inputs: d-major ``z0`` ``[N, S*D]``, ``h0``
    ``[N, rec]``, noise ``gz_dm`` ``[H, N, S*D]`` (d-major) and ``ga``
    ``[H, N, A]``. Returns ``(latents [H, N, S*D + rec] (z part d-major),
    actions [H, N, A])``, both f32. The final latents row ``[H-1]`` is
    UNWRITTEN (undefined) — it would hold the latent advanced past the last
    action, which every caller discards."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N = z0_dm.shape[0]
    SD = S * D
    if N % tile != 0:
        # largest divisor of N not exceeding the requested tile (a plain
        # gcd can silently collapse to 1-row tiles — a hidden perf cliff)
        tile = max(t for t in range(1, tile + 1) if N % t == 0)
    names = _pack_order(n_actor_layers)
    weights = [packed[k] for k in names]

    full = lambda arr: pl.BlockSpec(
        arr.shape, lambda i, t: (0,) * arr.ndim, memory_space=pltpu.VMEM
    )
    kernel = _make_kernel(H, S, D, A, rec, n_actor_layers, unimix,
                          dtype=weights[0].dtype)
    grid = (N // tile, H)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, SD), lambda i, t: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, rec), lambda i, t: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile, A), lambda i, t: (t, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile, SD), lambda i, t: (t, i, 0), memory_space=pltpu.VMEM),
            *[full(w) for w in weights],
        ],
        out_specs=(
            pl.BlockSpec((1, tile, SD + rec), lambda i, t: (t, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile, A), lambda i, t: (t, i, 0), memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((H, N, SD + rec), jnp.float32),
            jax.ShapeDtypeStruct((H, N, A), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((tile, SD), jnp.float32),
            pltpu.VMEM((tile, rec), jnp.float32),
        ],
        # resident weights (~8 MB bf16) get double-buffered by the pipeline;
        # the default 16 MB scoped-vmem cap is too tight for that
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(z0_dm, h0, ga, gz_dm, *weights)
    return out
