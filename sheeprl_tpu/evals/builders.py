"""Shared eval-policy builder helpers.

The algorithm-specific ``algos/*/evaluate.py`` files register *builders*
(:func:`~sheeprl_tpu.evals.service.register_eval_builder`) that map a frozen
checkpoint to one batched greedy act function. The dreamer families (DV1,
DV2, DV3 and their P2E variants) all share the same player-fns contract —
``init_states(wm_params, n)`` / ``greedy_action(wm, actor, state, obs, key)``
over a leading-batch-axis state pytree — so their builders collapse onto
:func:`dreamer_eval_policy` here and only differ in agent construction and
pixel normalization.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.evals.service import EvalPolicy

__all__ = ["actions_dim_of", "dreamer_eval_policy"]


def actions_dim_of(action_space) -> Tuple[Tuple[int, ...], bool]:
    """``(actions_dim, is_continuous)`` with the same convention every
    train entrypoint uses (Box → shape, MultiDiscrete → nvec, Discrete →
    [n])."""
    import gymnasium as gym

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    return actions_dim, is_continuous


def dreamer_eval_policy(
    player_fns: Dict[str, Any],
    params: Dict[str, Any],
    cfg,
    is_continuous: bool,
    sample_actions: bool = False,
    normalize_fn: Optional[Callable] = None,
) -> EvalPolicy:
    """One batched eval policy over a dreamer-family player-fns dict.

    ``params`` must carry ``{"world_model", "actor"}`` (the caller resolves
    P2E's ``actor_task`` vs ``actor`` split). ``sample_actions=True`` routes
    through ``exploration_action`` with zero exploration noise — DV3's
    historical test-time behaviour, where the action is still a sample from
    the (near-deterministic) policy head rather than its mode.
    ``normalize_fn(obs, cnn_keys)`` overrides the /255 default (DV1/DV2 use
    /255 − 0.5).
    """
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.utils import normalize_obs_jnp, prepare_obs

    if normalize_fn is None:
        normalize_fn = normalize_obs_jnp
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    wm_params = params["world_model"]
    actor_params = params["actor"]
    act_fn = player_fns["exploration_action"] if sample_actions else player_fns["greedy_action"]

    def act(obs, state, key):
        n = int(np.asarray(next(iter(obs.values()))).shape[0])
        prepared = prepare_obs(obs, cnn_keys, mlp_keys, n)
        norm = normalize_fn(prepared, cnn_keys)
        if sample_actions:
            actions, state = act_fn(
                wm_params, actor_params, state, norm, key, jnp.float32(0.0)
            )
        else:
            actions, state = act_fn(wm_params, actor_params, state, norm, key)
        if is_continuous:
            real = np.concatenate([np.asarray(a) for a in actions], -1)
        else:
            real = np.stack(
                [np.argmax(np.asarray(a), axis=-1) for a in actions], axis=-1
            )
        return real, state

    def init_state(n: int):
        return player_fns["init_states"](wm_params, n)

    return EvalPolicy(act=act, init_state=init_state)
