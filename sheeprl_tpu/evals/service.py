"""Parallel frozen-policy evaluation service.

One evaluation pipeline all algorithm families ride, replacing the twelve
copy-pasted ``algos/*/evaluate.py`` single-env while-loops:

- **Checkpoint resolution** goes through the ``sheeprl_tpu.ckpt`` manifest
  layer (``fabric.load`` verifies per-array checksums for manifest
  checkpoints); the run's persisted config supplies the agent architecture.
- **Agent rebuild** is a per-family *builder* registered with
  :func:`register_eval_builder` — the only algorithm-specific code left in
  an ``evaluate.py`` file. A builder returns an :class:`EvalPolicy`: one
  batched, jitted act function plus (for recurrent families) an initial
  state factory.
- **Episodes run in parallel**: N ≥ 10 deterministic episodes, one env per
  episode with per-episode seeds ``seed0 + i``, stepped as a single vector
  pool (sync or the PR-5 async shared-memory pool — ``eval.vectorization``)
  with **batched policy inference** (SEED-RL shape: one device program per
  step for the whole episode batch, not one per episode). Each episode's
  return freezes at its first termination, so pool autoreset never leaks
  post-episode reward and the same seed yields bitwise-identical returns on
  any backend.
- **Artifacts**: a versioned ``eval.json`` (per-episode returns, seeds,
  config hash, policy version, mean ± std ± IQM — the n≥10 /
  interquartile-mean protocol of Agarwal et al., NeurIPS 2021) and an
  append to the model registry (:mod:`sheeprl_tpu.evals.registry`).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.utils.utils import dotdict

__all__ = [
    "EvalPolicy",
    "EvalService",
    "register_eval_builder",
    "find_eval_builder",
    "registered_eval_builders",
    "eval_settings",
    "run_parallel_episodes",
    "run_eval_entrypoint",
    "evaluate_checkpoint",
    "iqm",
    "EVAL_SCHEMA",
]

#: schema tag stamped on every eval.json (bump on breaking layout changes)
EVAL_SCHEMA = "sheeprl_tpu/eval/v1"

#: shipped defaults for the ``eval`` config group — also the fallbacks when
#: evaluating a checkpoint whose persisted run config predates the group
_EVAL_DEFAULTS: Dict[str, Any] = {
    "episodes": 10,
    "seed0": 1000,
    "vectorization": None,  # null → inherit env.vectorization / env.sync_env
    "max_steps": 0,  # 0 → rely on the env's own TimeLimit
    "every_n_steps": 0,  # 0 → in-run eval off
    "inrun_episodes": 2,
    "write_json": True,
    "write_registry": True,
    "registry_dir": "logs/registry",
}


# ---------------------------------------------------------------------------
# builder registry (mirrors utils/registry's evaluation_registry shape)
# ---------------------------------------------------------------------------

_EVAL_BUILDERS: Dict[str, Callable] = {}


def register_eval_builder(algorithms: Sequence[str]):
    """Class/function decorator: register an eval-policy builder for one or
    more algorithm names. A builder has the signature
    ``(fabric, cfg, state, observation_space, action_space) -> EvalPolicy``.
    """

    def decorator(fn: Callable) -> Callable:
        for name in algorithms:
            _EVAL_BUILDERS[str(name)] = fn
        return fn

    return decorator


def find_eval_builder(algo_name: str) -> Optional[Callable]:
    return _EVAL_BUILDERS.get(str(algo_name))


def registered_eval_builders() -> List[str]:
    return sorted(_EVAL_BUILDERS)


@dataclass
class EvalPolicy:
    """The frozen agent as the service sees it — family-agnostic.

    ``act(obs, state, key) -> (real_actions, new_state)``: ``obs`` is the
    raw batched observation dict from the vector pool (leading axis =
    episode batch), ``real_actions`` a numpy array the pool can step
    (``reshape((B,) + single_action_space.shape)`` is applied by the
    runner). ``init_state(n)`` builds the recurrent state for an n-episode
    batch (None for stateless families). ``reset(state, keep)`` re-seeds
    finished rows (``keep`` is a bool [B] mask, False = row just finished);
    when omitted, a generic ``where(keep, state, init_state(n))`` over
    leading-batch-axis leaves is used.
    """

    act: Callable[[Dict[str, np.ndarray], Any, Any], Tuple[np.ndarray, Any]]
    init_state: Optional[Callable[[int], Any]] = None
    reset: Optional[Callable[[Any, np.ndarray], Any]] = None


def eval_settings(cfg) -> dotdict:
    """The run's ``eval`` knobs with shipped defaults filled in (persisted
    configs from runs that predate the ``eval`` group compose cleanly)."""
    merged = dict(_EVAL_DEFAULTS)
    try:
        user = cfg.get("eval", {}) or {}
    except AttributeError:
        user = {}
    for key, value in dict(user).items():
        merged[key] = value
    return dotdict(merged)


def iqm(values: Sequence[float]) -> float:
    """Interquartile mean: the mean of the middle 50% of episode returns
    (Agarwal et al. 2021's recommended point estimate — robust to the
    outlier episodes that dominate plain means at small n)."""
    x = np.sort(np.asarray(values, dtype=np.float64))
    n = x.size
    if n == 0:
        return float("nan")
    k = int(np.floor(n * 0.25))
    trimmed = x[k : n - k] if n - 2 * k > 0 else x
    return float(trimmed.mean())


# ---------------------------------------------------------------------------
# parallel episode runner
# ---------------------------------------------------------------------------


def _generic_reset(init_state_fn: Callable[[int], Any], n: int):
    """Default recurrent-state reset: replace finished rows with fresh
    initial state, assuming every leaf carries the episode batch on axis 0
    (true for all in-tree families; builders with exotic layouts pass an
    explicit ``reset``)."""
    import jax

    def reset(state, keep: np.ndarray):
        fresh = init_state_fn(n)

        def mask(cur, init):
            cur_arr = np.asarray(cur)
            init_arr = np.asarray(init)
            k = keep.reshape((n,) + (1,) * (cur_arr.ndim - 1))
            return np.where(k, cur_arr, init_arr)

        return jax.tree.map(mask, state, fresh)

    return reset


def run_parallel_episodes(
    policy: EvalPolicy,
    pool,
    seeds: Sequence[int],
    key,
    single_action_shape: Tuple[int, ...],
    max_steps: int = 0,
    dry_run: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Step the whole episode batch until every episode has terminated once.

    Returns ``(returns, lengths)`` (float64 / int64 arrays, one entry per
    episode). Episode i's return accumulates only while it is *alive* —
    frozen at the first ``terminated|truncated`` — so the pool's SAME_STEP
    autoreset can keep finished slots busy without polluting results, and
    the figures are independent of which backend stepped the pool.
    """
    import jax

    n = len(seeds)
    obs, _ = pool.reset(seed=[int(s) for s in seeds])
    state = policy.init_state(n) if policy.init_state is not None else None
    reset_fn = policy.reset
    if reset_fn is None and policy.init_state is not None:
        reset_fn = _generic_reset(policy.init_state, n)

    returns = np.zeros(n, dtype=np.float64)
    lengths = np.zeros(n, dtype=np.int64)
    alive = np.ones(n, dtype=bool)
    steps = 0
    while alive.any():
        key, act_key = jax.random.split(key)
        real_actions, state = policy.act(obs, state, act_key)
        real_actions = np.asarray(real_actions).reshape((n,) + tuple(single_action_shape))
        obs, rewards, terminated, truncated, _ = pool.step(real_actions)
        done = np.logical_or(
            np.asarray(terminated).reshape(n), np.asarray(truncated).reshape(n)
        )
        rewards = np.asarray(rewards, dtype=np.float64).reshape(n)
        returns += rewards * alive
        lengths += alive.astype(np.int64)
        alive &= ~done
        steps += 1
        if dry_run or (max_steps and steps >= max_steps):
            break
        if done.any() and alive.any() and state is not None and reset_fn is not None:
            # rows that finished re-enter via autoreset: hand them a fresh
            # recurrent state (their rewards no longer count, but a stale
            # state would make the batch composition run-order dependent)
            state = reset_fn(state, ~done)
    return returns, lengths


# ---------------------------------------------------------------------------
# pool construction
# ---------------------------------------------------------------------------


def make_eval_pool(cfg, log_dir: Optional[str], n: int, seed0: int, prefix: str = "test"):
    """One env per episode, wrapped exactly like the train-time factory's
    envs, vectorized with the configured backend (``eval.vectorization``
    overrides ``env.vectorization``/``env.sync_env`` for the eval pool
    only). Video capture, when enabled, follows the factory's gate: episode
    0 only."""
    from sheeprl_tpu.envs.vector.factory import vectorize_thunks
    from sheeprl_tpu.utils.env import make_env

    settings = eval_settings(cfg)
    pool_cfg = cfg
    if settings.vectorization is not None:
        pool_cfg = dotdict(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
        pool_cfg.env.vectorization = settings.vectorization
    seeds = [int(seed0) + i for i in range(n)]
    thunks = [
        make_env(
            pool_cfg,
            seeds[i],
            0,
            log_dir if i == 0 else None,
            prefix,
            vector_env_idx=i,
        )
        for i in range(n)
    ]
    pool = vectorize_thunks(
        thunks, pool_cfg, env_seeds_list=seeds, log_dir=log_dir, rank=0
    )
    return pool, seeds


def _probe_spaces(cfg):
    """Build one throwaway env to read the observation/action spaces (no
    log_dir: the probe must never trigger video capture)."""
    from sheeprl_tpu.envs.vector import make_eval_env

    env = make_eval_env(cfg, None)
    try:
        return env.observation_space, env.action_space
    finally:
        env.close()


def _policy_version_of(checkpoint: Optional[str]) -> Optional[int]:
    """The checkpoint's training step from its manifest, if resolvable."""
    if not checkpoint:
        return None
    try:
        from sheeprl_tpu.ckpt.manifest import read_manifest

        step = read_manifest(str(checkpoint)).get("step")
        return int(step) if step is not None else None
    except Exception:
        return None


def _config_hash_of(cfg, checkpoint: Optional[str]) -> Optional[str]:
    """Manifest hash when the checkpoint carries one (authoritative — the
    eval-time config mutates run_name/fabric and would hash differently),
    else the canonical hash of the config in hand."""
    if checkpoint:
        from sheeprl_tpu.evals.registry import _manifest_config_hash

        manifest_hash = _manifest_config_hash(str(checkpoint))
        if manifest_hash:
            return manifest_hash
    from sheeprl_tpu.evals.registry import registry_config_hash

    return registry_config_hash(cfg)


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------


class EvalService:
    """Run the frozen-greedy protocol for one policy and emit artifacts."""

    def __init__(self, cfg, log_dir: Optional[str] = None, fabric=None):
        self.cfg = cfg
        self.log_dir = log_dir
        self.fabric = fabric
        self.settings = eval_settings(cfg)

    def run(
        self,
        policy: EvalPolicy,
        checkpoint: Optional[str] = None,
        episodes: Optional[int] = None,
        seed0: Optional[int] = None,
        prefix: str = "test",
        write_json: Optional[bool] = None,
        write_registry: Optional[bool] = None,
        policy_version: Optional[int] = None,
    ) -> Dict[str, Any]:
        import gymnasium as gym
        import jax

        cfg = self.cfg
        settings = self.settings
        n = int(episodes if episodes is not None else settings.episodes)
        if n < 1:
            raise ValueError(f"eval.episodes must be >= 1, got {n}")
        seed0 = int(seed0 if seed0 is not None else settings.seed0)

        pool, seeds = make_eval_pool(cfg, self.log_dir, n, seed0, prefix=prefix)
        try:
            single_space = getattr(pool, "single_action_space", None)
            shape = tuple(single_space.shape) if single_space is not None else ()
            returns, lengths = run_parallel_episodes(
                policy,
                pool,
                seeds,
                jax.random.PRNGKey(seed0),
                shape,
                max_steps=int(settings.max_steps or 0),
                dry_run=bool(cfg.get("dry_run", False)),
            )
        finally:
            pool.close()

        if policy_version is None:
            policy_version = _policy_version_of(checkpoint)
        result: Dict[str, Any] = {
            "schema": EVAL_SCHEMA,
            "algo": str(cfg.algo.name),
            "env": str(cfg.env.id),
            "run": str(cfg.get("run_name", "")),
            "checkpoint": os.path.abspath(str(checkpoint)) if checkpoint else None,
            "config_hash": _config_hash_of(cfg, checkpoint),
            "policy_version": policy_version,
            "protocol": "frozen-greedy",
            "n": n,
            "seed0": seed0,
            "seeds": [int(s) for s in seeds],
            "returns": [float(r) for r in returns],
            "lengths": [int(l) for l in lengths],
            "mean": float(np.mean(returns)),
            "std": float(np.std(returns)),
            "iqm": iqm(returns),
            "min": float(np.min(returns)),
            "max": float(np.max(returns)),
        }

        from sheeprl_tpu.obs.counters import add_eval_episodes, add_eval_rounds

        add_eval_rounds(1)
        add_eval_episodes(n)

        if write_json is None:
            write_json = bool(settings.write_json)
        if write_json and self.log_dir:
            result["path"] = self._write_json(result)
        if write_registry is None:
            write_registry = bool(settings.write_registry)
        if write_registry and result["checkpoint"]:
            self._append_registry(result)
        return result

    def _write_json(self, result: Dict[str, Any]) -> str:
        """Atomic, non-clobbering ``eval.json`` (then ``eval_<k>.json``)."""
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, "eval.json")
        k = 1
        while os.path.exists(path):
            path = os.path.join(self.log_dir, f"eval_{k}.json")
            k += 1
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def _append_registry(self, result: Dict[str, Any]) -> None:
        from sheeprl_tpu.evals.registry import ModelRegistry

        registry = ModelRegistry(str(self.settings.registry_dir))
        try:
            registry.append(
                {
                    "run": result["run"] or os.path.basename(os.path.dirname(result["checkpoint"])),
                    "checkpoint": result["checkpoint"],
                    "env": result["env"],
                    "algo": result["algo"],
                    "config_hash": result["config_hash"],
                    "policy_version": result["policy_version"],
                    "protocol": result["protocol"],
                    "seed0": result["seed0"],
                    "metrics": {
                        "mean": result["mean"],
                        "std": result["std"],
                        "iqm": result["iqm"],
                        "n": result["n"],
                    },
                }
            )
        except Exception as exc:  # registry is an artifact, not a gate
            import warnings

            warnings.warn(f"model-registry append failed: {exc}")


# ---------------------------------------------------------------------------
# entrypoints
# ---------------------------------------------------------------------------


def run_eval_entrypoint(fabric, cfg, state: Dict[str, Any]) -> Dict[str, Any]:
    """The shared body of every ``algos/*/evaluate.py`` entrypoint: logger,
    space probe, builder lookup, service run, metric logging."""
    from sheeprl_tpu.utils.logger import create_tensorboard_logger

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))

    builder = find_eval_builder(cfg.algo.name)
    if builder is None:
        raise RuntimeError(
            f"No eval-policy builder registered for '{cfg.algo.name}'. "
            f"Registered: {registered_eval_builders()}"
        )
    observation_space, action_space = _probe_spaces(cfg)
    policy = builder(fabric, cfg, state, observation_space, action_space)

    service = EvalService(cfg, log_dir=log_dir, fabric=fabric)
    result = service.run(policy, checkpoint=cfg.get("checkpoint_path"))
    fabric.print(
        f"Test - {result['n']} episodes: mean={result['mean']:.2f} "
        f"std={result['std']:.2f} iqm={result['iqm']:.2f}"
    )
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": result["mean"]}, 0)
    return result


def evaluate_checkpoint(
    checkpoint_path: str,
    episodes: Optional[int] = None,
    seed0: Optional[int] = None,
    write_json: bool = False,
    write_registry: Optional[bool] = None,
    registry_dir: Optional[str] = None,
    capture_video: bool = False,
    vectorization: Optional[str] = None,
    state: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Standalone service entry: checkpoint path in, eval result out.

    Used by ``tools/bench_matrix.py`` cells, the in-run eval child process
    (which passes published ``state`` directly), and ad-hoc re-scoring. The
    run's persisted config supplies the agent; fabric is forced to one
    device like the eval CLI.
    """
    import jax

    import sheeprl_tpu
    from sheeprl_tpu.cli import _load_run_config
    from sheeprl_tpu.config.instantiate import instantiate

    sheeprl_tpu.register_algorithms()
    cfg, log_dir = _load_run_config(checkpoint_path)
    cfg.env.capture_video = bool(capture_video)
    eval_cfg = eval_settings(cfg)
    if vectorization is not None:
        eval_cfg.vectorization = vectorization
    if registry_dir is not None:
        eval_cfg.registry_dir = registry_dir
    cfg["eval"] = eval_cfg
    run_fabric = cfg.get("fabric", {}) or {}
    cfg.fabric = dotdict(
        {
            "_target_": "sheeprl_tpu.fabric.Fabric",
            "devices": 1,
            "num_nodes": 1,
            "strategy": "auto",
            "accelerator": "auto",
            "precision": run_fabric.get("precision", "32-true"),
            "prng_impl": run_fabric.get("prng_impl", "rbg"),
            "callbacks": [],
        }
    )
    fabric = instantiate(cfg.fabric)
    if state is None:
        state = fabric.load(checkpoint_path)

    builder = find_eval_builder(cfg.algo.name)
    if builder is None:
        raise RuntimeError(
            f"No eval-policy builder registered for '{cfg.algo.name}'. "
            f"Registered: {registered_eval_builders()}"
        )
    observation_space, action_space = _probe_spaces(cfg)
    policy = builder(fabric, cfg, state, observation_space, action_space)
    service = EvalService(cfg, log_dir=log_dir if write_json else None, fabric=fabric)
    return service.run(
        policy,
        checkpoint=checkpoint_path,
        episodes=episodes,
        seed0=seed0,
        write_json=write_json,
        write_registry=write_registry,
    )
