"""File-based model registry: an append-only ``registry.jsonl``.

The reference repo's v0.5.x ``model_manager`` answers "which checkpoint is
the published model for <env>, <algo>?" with a mutable directory tree; this
registry answers the same question with one append-only JSONL file so that
(a) concurrent writers never corrupt each other past a torn final line,
(b) history is never rewritten — every eval round of every run stays
diffable, and (c) `best()` resolution is a pure fold over the file.

One line per evaluation: ``(run, checkpoint, env, algo, config_hash,
metrics)`` plus the eval protocol fields the service emits (seeds, n,
mean/std/iqm). Appends are ``write → flush → fsync`` of a single line, so a
crash can only tear the *last* line; :meth:`ModelRegistry.scan` tolerates
exactly that (a torn tail parses as garbage and is skipped, everything
before it survives).

Config-hash integrity: when the record points at a manifest checkpoint
(``sheeprl_tpu.ckpt`` layout) whose ``manifest.json`` carries a
``config_hash``, an append with a *different* hash is rejected — a registry
row must describe the run that produced the weights, not whatever config
happened to be composed at eval time (the version-skew trap the SURVEY
notes about the reference's model manager).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "ModelRegistry",
    "RegistryError",
    "REGISTRY_SCHEMA",
    "registry_config_hash",
    "resolve_checkpoint_ref",
]

#: schema tag stamped on every record (bump on breaking layout changes)
REGISTRY_SCHEMA = "sheeprl_tpu/registry/v1"

#: fields every record must carry to be appendable
REQUIRED_FIELDS = ("run", "checkpoint", "env", "algo", "metrics")


class RegistryError(RuntimeError):
    """A record failed validation (missing fields, config-hash mismatch)."""


def registry_config_hash(cfg) -> Optional[str]:
    """The canonical run-config hash — same recipe the checkpoint manager
    stamps into ``manifest.json`` (ckpt/manager.py), so registry rows and
    manifests agree byte-for-byte when hashing the same config."""
    try:
        import hashlib

        from sheeprl_tpu.config.engine import to_yaml

        return hashlib.sha256(to_yaml(cfg).encode()).hexdigest()[:16]
    except Exception:
        return None


def _manifest_config_hash(checkpoint: str) -> Optional[str]:
    """``config_hash`` from a manifest checkpoint dir, else None."""
    path = os.path.join(str(checkpoint), "manifest.json")
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    value = manifest.get("config_hash") if isinstance(manifest, dict) else None
    return str(value) if value else None


def resolve_checkpoint_ref(
    ref: str, registry_dir: str = "logs/registry"
) -> Tuple[str, Optional[Dict[str, Any]]]:
    """Resolve a checkpoint reference to a concrete path.

    ``registry:best:<algo>:<env id>`` resolves through :meth:`ModelRegistry.
    best` (deterministic mean/n/append-order ranking) against
    ``registry_dir``; anything else is already a path. Returns
    ``(checkpoint_path, registry_record_or_None)`` so callers can surface
    the resolved record (the eval CLI prints it, the serving gateway stamps
    it into its status). Shared by ``cli.evaluation`` and
    ``sheeprl_tpu.serve`` — the one place the ref grammar lives.
    """
    ref = str(ref)
    if not ref.startswith("registry:"):
        return ref, None
    parts = ref.split(":")
    if len(parts) != 4 or parts[1] != "best":
        raise ValueError(
            "registry checkpoint refs look like registry:best:<algo>:<env id>, "
            f"got {ref!r}"
        )
    registry = ModelRegistry(str(registry_dir))
    record = registry.best(env=parts[3], algo=parts[2])
    if record is None:
        raise ValueError(
            f"no registry record for algo={parts[2]!r} env={parts[3]!r} "
            f"in {registry.path}"
        )
    return str(record["checkpoint"]), record


class ModelRegistry:
    """Append-only JSONL model registry rooted at ``root/registry.jsonl``."""

    def __init__(self, root: str):
        self.root = str(root)
        self.path = os.path.join(self.root, "registry.jsonl")

    # ------------------------------------------------------------------ write

    def append(self, record: Dict[str, Any], verify: bool = True) -> Dict[str, Any]:
        """Validate and append one record; returns the stamped record.

        ``verify=True`` cross-checks ``record["config_hash"]`` against the
        checkpoint's manifest when both exist — mismatch raises
        :class:`RegistryError` instead of poisoning the file.
        """
        rec = dict(record)
        rec.setdefault("schema", REGISTRY_SCHEMA)
        missing = [k for k in REQUIRED_FIELDS if not rec.get(k)]
        if missing:
            raise RegistryError(f"registry record missing fields: {missing}")
        metrics = rec.get("metrics")
        if not isinstance(metrics, dict) or not isinstance(
            metrics.get("mean"), (int, float)
        ):
            raise RegistryError("registry record needs metrics.mean (a number)")
        if verify:
            manifest_hash = _manifest_config_hash(rec["checkpoint"])
            rec_hash = rec.get("config_hash")
            if manifest_hash and rec_hash and str(rec_hash) != manifest_hash:
                raise RegistryError(
                    f"config_hash mismatch for {rec['checkpoint']}: record has "
                    f"{rec_hash}, manifest says {manifest_hash} — refusing to "
                    "register eval metrics against weights from a different config"
                )
            if manifest_hash and not rec_hash:
                rec["config_hash"] = manifest_hash
        line = json.dumps(rec, sort_keys=True, default=float)
        os.makedirs(self.root, exist_ok=True)
        # single write + fsync: a crash tears at most this (final) line, which
        # scan() then skips — all previously fsynced lines stay intact
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        return rec

    # ------------------------------------------------------------------- read

    def scan(self) -> List[Dict[str, Any]]:
        """All parseable records in append order; torn/garbage lines skipped."""
        records: List[Dict[str, Any]] = []
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                for raw in f:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        rec = json.loads(raw)
                    except json.JSONDecodeError:
                        continue  # torn tail (or hand-edited garbage)
                    if isinstance(rec, dict):
                        records.append(rec)
        except FileNotFoundError:
            return []
        return records

    def records(
        self, env: Optional[str] = None, algo: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        """Scan filtered by env id and/or algo name."""
        out = []
        for rec in self.scan():
            if env is not None and str(rec.get("env")) != str(env):
                continue
            if algo is not None and str(rec.get("algo")) != str(algo):
                continue
            out.append(rec)
        return out

    def best(self, env: str, algo: str) -> Optional[Dict[str, Any]]:
        """The best record for ``(env, algo)`` — deterministic resolution.

        Ranking: highest ``metrics.mean``; ties broken by larger episode
        count ``metrics.n`` (more evidence wins); remaining ties by append
        order (the later record wins — it is the one an operator most
        recently produced and can regenerate).
        """
        best_rec: Optional[Dict[str, Any]] = None
        best_key = None
        for idx, rec in enumerate(self.records(env=env, algo=algo)):
            metrics = rec.get("metrics") or {}
            mean = metrics.get("mean")
            if not isinstance(mean, (int, float)):
                continue
            n = metrics.get("n")
            key = (float(mean), int(n) if isinstance(n, (int, float)) else 0, idx)
            if best_key is None or key > best_key:
                best_key, best_rec = key, rec
        return best_rec
