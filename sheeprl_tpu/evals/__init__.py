"""Evaluation & model-registry subsystem (howto/evaluation.md).

- :mod:`sheeprl_tpu.evals.service` — the parallel frozen-policy eval
  service every ``algos/*/evaluate.py`` entrypoint rides.
- :mod:`sheeprl_tpu.evals.registry` — the append-only ``registry.jsonl``
  model registry with deterministic ``best(env, algo)`` resolution.
- :mod:`sheeprl_tpu.evals.inrun` — periodic in-run eval in a separate
  process, fed by the policy-publication channel (off the critical path).
"""

from sheeprl_tpu.evals.registry import ModelRegistry, RegistryError
from sheeprl_tpu.evals.service import (
    EvalPolicy,
    EvalService,
    eval_settings,
    evaluate_checkpoint,
    find_eval_builder,
    iqm,
    register_eval_builder,
    run_eval_entrypoint,
)

__all__ = [
    "ModelRegistry",
    "RegistryError",
    "EvalPolicy",
    "EvalService",
    "eval_settings",
    "evaluate_checkpoint",
    "find_eval_builder",
    "iqm",
    "register_eval_builder",
    "run_eval_entrypoint",
]
