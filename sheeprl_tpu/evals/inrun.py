"""Periodic in-run evaluation, off the train-step critical path.

The trainer never runs an eval episode. Instead, rank 0 publishes the
policy every ``eval.every_n_steps`` policy steps through the plane's
:class:`~sheeprl_tpu.plane.publish.PolicyPublisher` (``async_publish=True``
— the npz write happens on the publisher's writer thread), and a separate
**eval process** polls the channel with
:class:`~sheeprl_tpu.plane.publish.PolicyPoller`, rebuilds the frozen agent
via the same builder registry the eval CLI uses, runs a few greedy
episodes, and drops the growing frozen-greedy curve into
``telemetry/sidecar_evalproc.json``. The run's own telemetry plane
(obs/dist/aggregate) folds that sidecar into ``live.json`` mid-run and
``telemetry.json`` at finalize under ``sources.evalproc`` — so eval curves
appear in the run artifacts while the train phase histograms stay
untouched (the off-critical-path evidence the subsystem is gated on).

The child pins jax to the CPU backend before importing it (eval must never
fight the trainer for the mesh) and forces a sync eval pool (a daemonic
process cannot own env worker pools). Algorithms call only
:func:`maybe_start_inrun_eval` / :meth:`InRunEval.maybe_publish` /
:meth:`InRunEval.close` — all process machinery lives here, outside
``algos/`` (tools/lint_plane.py).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

__all__ = ["InRunEval", "maybe_start_inrun_eval"]


class _ChildHalt:
    """Event-like ``stop | orphaned`` view for the child's blocking waits."""

    def __init__(self, stop, parent_pid: int):
        self._stop = stop
        self._parent_pid = int(parent_pid)

    def is_set(self) -> bool:
        if self._stop is not None and self._stop.is_set():
            return True
        # parent death without close(): getppid() re-parents to init/reaper
        return os.getppid() != self._parent_pid


def child_main(spec: Dict[str, Any]) -> None:
    """Eval-process entry point (spawned, never forked)."""
    # the evaluator must soak idle cycles, not race the trainer for them —
    # on a host whose cores the trainer saturates (CPU meshes, few-core
    # boxes) a same-priority child shows up directly in the train-phase
    # tails. SCHED_IDLE runs the child only when nothing else wants the
    # CPU; nice(19) is the portable fallback.
    try:
        os.sched_setscheduler(0, os.SCHED_IDLE, os.sched_param(0))
    except (AttributeError, OSError, PermissionError):
        try:
            os.nice(19)
        except OSError:
            pass
    # before ANY jax import: the eval child lives on the host CPU
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    if spec.get("prng_impl"):
        jax.config.update("jax_default_prng_impl", str(spec["prng_impl"]))

    import numpy as np

    import sheeprl_tpu
    from sheeprl_tpu.obs.dist.aggregate import write_sidecar
    from sheeprl_tpu.plane.publish import PolicyPoller
    from sheeprl_tpu.plane.slabs import PlaneClosed
    from sheeprl_tpu.utils.utils import dotdict

    sheeprl_tpu.register_algorithms()

    from sheeprl_tpu.evals.service import (
        _probe_spaces,
        find_eval_builder,
        make_eval_pool,
        run_parallel_episodes,
    )

    cfg = dotdict(spec["cfg"])
    cfg.env.capture_video = False
    eval_overrides = dict(cfg.get("eval", {}) or {})
    eval_overrides["vectorization"] = "sync"  # daemonic child: no worker pools
    cfg["eval"] = eval_overrides

    halt = _ChildHalt(spec.get("stop"), spec["parent_pid"])
    episodes = max(int(spec.get("episodes", 2)), 1)
    seed0 = int(spec.get("seed0", 1000))
    tel_dir = spec["tel_dir"]
    builder = find_eval_builder(cfg.algo.name)
    if builder is None:
        write_sidecar(
            tel_dir,
            "evalproc",
            {"error": f"no eval builder for {cfg.algo.name!r}", "points": []},
        )
        return

    observation_space, action_space = _probe_spaces(cfg)
    pool, seeds = make_eval_pool(cfg, None, episodes, seed0, prefix="inrun")
    single_space = getattr(pool, "single_action_space", None)
    act_shape = tuple(single_space.shape) if single_space is not None else ()
    poller = PolicyPoller(spec["policy_root"])
    points = []
    try:
        version = -1
        while not halt.is_set():
            try:
                version, params = poller.wait_min_version(
                    version + 1, stop=halt, use_exact=False
                )
            except PlaneClosed:
                break
            import time

            t0 = time.monotonic()
            policy = builder(None, cfg, params, observation_space, action_space)
            returns, lengths = run_parallel_episodes(
                policy,
                pool,
                seeds,
                jax.random.PRNGKey(seed0),
                act_shape,
                max_steps=int(eval_overrides.get("max_steps", 0) or 0),
            )
            points.append(
                {
                    "policy_version": int(version),
                    "mean": float(np.mean(returns)),
                    "std": float(np.std(returns)),
                    "episodes": int(episodes),
                    "eval_wall_s": round(time.monotonic() - t0, 3),
                }
            )
            write_sidecar(
                tel_dir,
                "evalproc",
                {
                    "protocol": "frozen-greedy",
                    "episodes": episodes,
                    "seed0": seed0,
                    "rounds": len(points),
                    "points": points[-200:],
                    "last_mean": points[-1]["mean"],
                    "last_policy_version": points[-1]["policy_version"],
                },
            )
    finally:
        pool.close()


class InRunEval:
    """Rank-0 handle: gated async policy publication + the eval process."""

    def __init__(self, cfg, log_dir: str):
        from sheeprl_tpu.evals.service import eval_settings
        from sheeprl_tpu.plane.publish import PolicyPublisher

        settings = eval_settings(cfg)
        self.every_n_steps = int(settings.every_n_steps)
        self.policy_root = os.path.join(log_dir, "inrun_policies")
        self.tel_dir = os.path.join(log_dir, "telemetry")
        self._last_version: Optional[int] = None
        self._publisher = PolicyPublisher(
            self.policy_root,
            keep_policies=2,
            algo=str(cfg.algo.name),
            async_publish=True,
        )
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self._stop = ctx.Event()
        spec = {
            "cfg": cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg),
            "policy_root": self.policy_root,
            "tel_dir": self.tel_dir,
            "episodes": int(settings.inrun_episodes),
            "seed0": int(settings.seed0),
            "stop": self._stop,
            "parent_pid": os.getpid(),
            "prng_impl": (cfg.get("fabric", {}) or {}).get("prng_impl"),
        }
        self._child = ctx.Process(
            target=child_main, args=(spec,), daemon=True, name="inrun-eval"
        )
        self._child.start()

    def due(self, policy_step: int) -> bool:
        """Cheap pre-gate so callers can skip building the publish pytree
        (a ``device_get``, typically) when the step gate is closed."""
        policy_step = int(policy_step)
        return self._last_version is None or (
            policy_step - self._last_version >= self.every_n_steps
            and policy_step > self._last_version
        )

    def maybe_publish(self, policy_step: int, state: Any) -> bool:
        """Publish ``state`` as version ``policy_step`` when the step gate
        opens. ``state`` must be a host pytree shaped like the checkpoint
        layout the algo's eval builder expects. Returns True on publish."""
        policy_step = int(policy_step)
        if not self.due(policy_step):
            return False
        self._publisher.publish(policy_step, state)
        self._last_version = policy_step
        from sheeprl_tpu.obs.counters import add_inrun_eval_publishes

        add_inrun_eval_publishes(1)
        return True

    def close(self, timeout: float = 30.0) -> None:
        """Stop the eval process and flush pending publications."""
        self._stop.set()
        try:
            self._publisher.close(timeout=timeout)
        finally:
            self._child.join(timeout=timeout)
            if self._child.is_alive():
                self._child.terminate()
                self._child.join(timeout=5.0)


def maybe_start_inrun_eval(fabric, cfg, log_dir: Optional[str]) -> Optional[InRunEval]:
    """The one call an algorithm makes: returns a handle when in-run eval is
    enabled (``eval.every_n_steps > 0``) on global rank 0, else None."""
    from sheeprl_tpu.evals.service import eval_settings

    settings = eval_settings(cfg)
    if int(settings.every_n_steps or 0) <= 0 or not log_dir:
        return None
    if fabric is not None and not getattr(fabric, "is_global_zero", True):
        return None
    return InRunEval(cfg, log_dir)
