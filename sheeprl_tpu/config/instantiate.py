"""`_target_`-style object instantiation (hydra.utils.instantiate parity).

The reference builds objects straight from config via ``_target_`` +
``hydra.utils.instantiate`` (cli.py:93, ppo.py:192, utils/env.py:72). We keep
that surface, plus an alias table so *reference* config trees (pointing at
``sheeprl.*`` / ``torch*`` / ``lightning*`` classes) resolve to their TPU-native
equivalents — this is what makes the reference's own recipes runnable here.
"""

from __future__ import annotations

import importlib
from functools import partial
from typing import Any, Dict

# reference class path -> tpu-native class path
TARGET_ALIASES: Dict[str, str] = {
    "lightning.fabric.Fabric": "sheeprl_tpu.fabric.Fabric",
    "sheeprl.utils.callback.CheckpointCallback": "sheeprl_tpu.utils.callback.CheckpointCallback",
    "sheeprl.utils.metric.MetricAggregator": "sheeprl_tpu.utils.metric.MetricAggregator",
    "torchmetrics.MeanMetric": "sheeprl_tpu.utils.metric.MeanMetric",
    "torchmetrics.SumMetric": "sheeprl_tpu.utils.metric.SumMetric",
    "torchmetrics.MaxMetric": "sheeprl_tpu.utils.metric.MaxMetric",
    "torchmetrics.MinMetric": "sheeprl_tpu.utils.metric.MinMetric",
    "torch.optim.Adam": "sheeprl_tpu.utils.optim.Adam",
    "torch.optim.AdamW": "sheeprl_tpu.utils.optim.AdamW",
    "torch.optim.SGD": "sheeprl_tpu.utils.optim.SGD",
    "gym.make": "gymnasium.make",
}
# any other `sheeprl.` path maps onto the same path under `sheeprl_tpu.`
_PREFIX_ALIASES = {"sheeprl.": "sheeprl_tpu."}


def resolve_target(path: str) -> Any:
    path = TARGET_ALIASES.get(path, path)
    for old, new in _PREFIX_ALIASES.items():
        if path.startswith(old):
            path = new + path[len(old):]
            break
    module_name, _, attr = path.rpartition(".")
    if not module_name:
        raise ValueError(f"Cannot resolve target '{path}'")
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def instantiate(cfg: Any, *args: Any, **kwargs: Any) -> Any:
    """Build the object described by ``cfg['_target_']``.

    Remaining keys become keyword arguments (nested ``_target_`` dicts are
    instantiated recursively). ``_partial_: true`` returns a functools.partial.
    """
    if cfg is None:
        return None
    if isinstance(cfg, (list, tuple)):
        return [instantiate(c) for c in cfg]
    if not isinstance(cfg, dict):
        raise TypeError(f"instantiate expects a dict with _target_, got {type(cfg)}")
    cfg = dict(cfg)
    target = cfg.pop("_target_", None)
    if target is None:
        raise ValueError(f"Missing _target_ in config: {cfg}")
    is_partial = bool(cfg.pop("_partial_", False))
    cfg.pop("_convert_", None)
    fn = resolve_target(target)

    def convert(v):
        # hydra's _recursive_=True default: instantiate _target_ dicts found
        # anywhere inside plain containers too (metrics dicts, callback lists)
        if isinstance(v, dict):
            if "_target_" in v:
                return instantiate(v)
            return {k: convert(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [convert(x) for x in v]
        return v

    final_kwargs = {k: convert(v) for k, v in cfg.items()}
    final_kwargs.update(kwargs)
    if is_partial:
        return partial(fn, *args, **final_kwargs)
    return fn(*args, **final_kwargs)
