"""Mini-Hydra: yaml config composition with the reference's surface.

The reference drives everything through Hydra 1.3 (`sheeprl/configs/config.yaml`,
search-path plugin `hydra_plugins/sheeprl_search_path.py:26-33`). Hydra is not
available in this environment, so this module re-implements the subset the
recipes actually use:

- defaults lists with ``_self_`` ordering, relative (``default``) and absolute
  (``/optim@optimizer: adam``) entries, ``override /group: option`` directives,
  and mandatory ``???`` group choices;
- ``# @package _global_`` / ``# @package some.path`` headers;
- CLI override grammar ``group=option``, ``a.b.c=value``, ``+a.b=value``,
  ``~a.b``;
- ``${a.b.c}`` interpolation (typed when the whole string is one reference) and
  the ``${now:...}`` resolver;
- ``SHEEPRL_SEARCH_PATH`` with ``file://`` and ``pkg://`` entries so user
  projects can add configs without forking (reference plugin behavior).

Scientific-notation floats (``2e-4``) are parsed as floats, matching OmegaConf
rather than bare PyYAML 1.1.
"""

from __future__ import annotations

import datetime
import importlib
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import yaml

from sheeprl_tpu.utils.utils import dotdict

MISSING = "???"

# ---------------------------------------------------------------------------
# yaml loading with OmegaConf-style float resolution
# ---------------------------------------------------------------------------


class _ConfigLoader(yaml.SafeLoader):
    pass


_ConfigLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:
            [-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
            |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
            |[-+]?\.[0-9_]+(?:[eE][-+]?[0-9]+)?
            |[-+]?\.(?:inf|Inf|INF)
            |\.(?:nan|NaN|NAN)
        )$""",
        re.X,
    ),
    list("-+0123456789."),
)


def yaml_load(text: str) -> Any:
    return yaml.load(text, Loader=_ConfigLoader)


# ---------------------------------------------------------------------------
# search path
# ---------------------------------------------------------------------------

SEARCH_PATH_ENV_VAR = "SHEEPRL_SEARCH_PATH"


def _builtin_config_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "configs")


def build_search_path(extra: Optional[Sequence[str]] = None) -> List[str]:
    """Ordered list of config roots; earlier entries win on lookup.

    As in the reference plugin (hydra_plugins/sheeprl_search_path.py:33, which
    *appends* user entries after the primary config dir), the builtin config
    tree comes first: user dirs add new options but cannot shadow builtins.
    """
    paths: List[str] = [_builtin_config_dir()]
    raw = []
    if extra:
        raw.extend(extra)
    env = os.environ.get(SEARCH_PATH_ENV_VAR, "")
    if env:
        raw.extend(p for p in env.split(";") if p)
    for entry in raw:
        if entry.startswith("file://"):
            p = os.path.abspath(entry[len("file://"):])
            if p not in paths:
                paths.append(p)
        elif entry.startswith("pkg://"):
            pkg = entry[len("pkg://"):]
            if pkg in ("sheeprl.configs", "sheeprl_tpu.configs"):
                continue  # builtin tree is already first
            try:
                mod = importlib.import_module(pkg)
                paths.append(os.path.dirname(os.path.abspath(mod.__file__)))
            except Exception:
                pass
        else:
            p = os.path.abspath(entry)
            if p not in paths:
                paths.append(p)
    return paths


# ---------------------------------------------------------------------------
# file model
# ---------------------------------------------------------------------------


class ConfigFile:
    def __init__(self, package: Optional[str], defaults: List[Any], body: Dict[str, Any]):
        self.package = package  # None = default (its own group path)
        self.defaults = defaults
        self.body = body


_PACKAGE_RE = re.compile(r"^#\s*@package\s+(\S+)\s*$", re.M)


def _load_config_file(search_path: List[str], group: str, name: str) -> ConfigFile:
    """Load ``<root>/<group>/<name>.yaml`` from the first root that has it."""
    name = name[:-5] if name.endswith(".yaml") else name
    rel = os.path.join(group, name + ".yaml") if group else name + ".yaml"
    for root in search_path:
        path = os.path.join(root, rel)
        if os.path.isfile(path):
            with open(path) as f:
                text = f.read()
            m = _PACKAGE_RE.search(text)
            package = m.group(1) if m else None
            data = yaml_load(text) or {}
            if not isinstance(data, dict):
                raise ValueError(f"Config file {path} must contain a mapping")
            defaults = data.pop("defaults", [])
            return ConfigFile(package, defaults, data)
    tried = [os.path.join(r, rel) for r in search_path]
    raise FileNotFoundError(
        f"Config '{rel}' not found in search path:\n  " + "\n  ".join(tried)
    )


def _config_exists(search_path: List[str], group: str, name: str) -> bool:
    name = name[:-5] if name.endswith(".yaml") else name
    rel = os.path.join(group, name + ".yaml") if group else name + ".yaml"
    return any(os.path.isfile(os.path.join(r, rel)) for r in search_path)


def _group_exists(search_path: List[str], group: str) -> bool:
    return any(os.path.isdir(os.path.join(r, group)) for r in search_path)


# ---------------------------------------------------------------------------
# defaults-entry parsing
# ---------------------------------------------------------------------------


class DefaultEntry:
    """One parsed defaults-list item."""

    def __init__(
        self,
        group: str,
        option: Any,
        package: Optional[str],
        is_override: bool,
        is_absolute: bool,
        is_self: bool = False,
    ):
        self.group = group          # group path, '/'-separated, no leading slash
        self.option = option        # option name, MISSING, or None (`- group: null` selects nothing)
        self.package = package      # explicit @package target (group-relative semantics)
        self.is_override = is_override
        self.is_absolute = is_absolute
        self.is_self = is_self


def _parse_default_entry(entry: Any, current_group: str) -> Optional[DefaultEntry]:
    """Parse a defaults item. Returns None for hydra-internal entries we skip."""
    if entry == "_self_":
        return DefaultEntry("", None, None, False, False, is_self=True)
    if isinstance(entry, str):
        # bare relative option in the same group, e.g. `- default`
        return DefaultEntry(current_group, entry, None, False, False)
    if isinstance(entry, dict):
        if len(entry) != 1:
            raise ValueError(f"Malformed defaults entry: {entry!r}")
        key, option = next(iter(entry.items()))
        key = key.strip()
        is_override = False
        if key.startswith("override "):
            is_override = True
            key = key[len("override "):].strip()
        if key.startswith("hydra/") or key == "hydra":
            return None  # hydra's own config groups don't apply here
        package = None
        if "@" in key:
            key, package = key.split("@", 1)
        is_absolute = key.startswith("/")
        group = key.lstrip("/")
        if not is_absolute and current_group:
            group = f"{current_group}/{group}" if group else current_group
        return DefaultEntry(group, option, package, is_override, is_absolute)
    raise ValueError(f"Malformed defaults entry: {entry!r}")


# ---------------------------------------------------------------------------
# merge helpers
# ---------------------------------------------------------------------------


def _deep_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> Dict[str, Any]:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v if not isinstance(v, dict) else _deep_copy(v)
    return dst


def _deep_copy(d):
    if isinstance(d, dict):
        return {k: _deep_copy(v) for k, v in d.items()}
    if isinstance(d, list):
        return [_deep_copy(v) for v in d]
    return d


def _merge_at(cfg: Dict[str, Any], package: str, body: Dict[str, Any]) -> None:
    """Merge ``body`` into ``cfg`` at dotted path ``package`` ('' = root)."""
    node = cfg
    if package:
        for part in package.split("."):
            node = node.setdefault(part, {})
            if not isinstance(node, dict):
                raise ValueError(f"Cannot merge into non-dict at '{package}'")
    _deep_merge(node, body)


def _set_path(cfg: Dict[str, Any], path: str, value: Any) -> None:
    parts = path.split(".")
    node = cfg
    for part in parts[:-1]:
        nxt = node.get(part)
        if not isinstance(nxt, dict):
            nxt = {}
            node[part] = nxt
        node = nxt
    node[parts[-1]] = value


def _del_path(cfg: Dict[str, Any], path: str) -> None:
    parts = path.split(".")
    node = cfg
    for part in parts[:-1]:
        node = node.get(part)
        if not isinstance(node, dict):
            return
    node.pop(parts[-1], None)


def _get_path(cfg: Dict[str, Any], path: str) -> Any:
    node = cfg
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            raise KeyError(path)
        node = node[part]
    return node


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------


def _effective_package(cfile: ConfigFile, entry_group: str, explicit_pkg: Optional[str]) -> str:
    """Where does this file's body merge? Priority: defaults-entry @pkg, file header, group path."""
    if explicit_pkg is not None:
        if explicit_pkg == "_global_":
            return ""
        return explicit_pkg
    if cfile.package is not None:
        if cfile.package == "_global_":
            return ""
        if cfile.package.startswith("_global_."):
            return cfile.package[len("_global_."):]
        return cfile.package
    return entry_group.replace("/", ".")


def _collect_choices(
    search_path: List[str],
    group: str,
    name: str,
    choices: Dict[str, str],
    cli_choices: Dict[str, str],
    depth: int = 0,
    is_root: bool = True,
) -> None:
    """Pre-pass: walk the defaults tree recording `override /g: opt` directives
    and default group choices, so late overrides (exp files) can retarget
    groups merged earlier — mirroring Hydra's two-phase defaults-tree build.

    Group choices (CLI `env=atari`, exp `override /env: atari`) target the
    *root* defaults list only; nested relative entries (e.g. `- default`
    inside `algo/ppo.yaml`) always use their literal option, as in Hydra.
    """
    if depth > 20:
        raise RecursionError("defaults tree too deep (cycle?)")
    cfile = _load_config_file(search_path, group, name)
    for raw in cfile.defaults:
        entry = _parse_default_entry(raw, group)
        if entry is None or entry.is_self or entry.option is None:
            continue
        g = entry.group
        if entry.is_override:
            if g not in cli_choices:
                choices[g] = entry.option
            continue
        if is_root:
            opt = cli_choices.get(g, choices.get(g, entry.option))
            if g not in choices:
                choices[g] = opt
        else:
            opt = entry.option
        if opt == MISSING:
            opt = cli_choices.get(g, choices.get(g))
            if opt in (None, MISSING):
                continue
        if _config_exists(search_path, g, opt):
            _collect_choices(search_path, g, opt, choices, cli_choices, depth + 1, is_root=False)


def _compose_file(
    search_path: List[str],
    group: str,
    name: str,
    entry_pkg: Optional[str],
    choices: Dict[str, str],
    cli_choices: Dict[str, str],
    cfg: Dict[str, Any],
    depth: int = 0,
    is_root: bool = True,
) -> None:
    """Merge ``group/name.yaml`` (with its defaults) into ``cfg`` in order."""
    if depth > 20:
        raise RecursionError("defaults tree too deep (cycle?)")
    cfile = _load_config_file(search_path, group, name)
    pkg = _effective_package(cfile, group, entry_pkg)

    entries = [_parse_default_entry(raw, group) for raw in cfile.defaults]
    entries = [e for e in entries if e is not None]
    has_self = any(e.is_self for e in entries)
    if not has_self:
        # Hydra 1.1+: implicit _self_ first — own body can be overridden by defaults
        entries.insert(0, DefaultEntry("", None, None, False, False, is_self=True))

    for entry in entries:
        if entry.is_self:
            _merge_at(cfg, pkg, _deep_copy(cfile.body))
            continue
        if entry.option is None:  # `- group: null` selects nothing
            continue
        if entry.is_override:
            continue  # handled in the pre-pass
        g = entry.group
        if is_root:
            opt = cli_choices.get(g, choices.get(g, entry.option))
        else:
            opt = entry.option
            if opt == MISSING:
                opt = cli_choices.get(g, choices.get(g, MISSING))
        if opt == MISSING:
            raise ValueError(
                f"You must specify '{g}', e.g, {g}=<OPTION>\nAvailable options:\n"
                + "\n".join("\t" + o for o in available_options(search_path, g))
            )
        if opt is None:
            continue
        # packages in nested defaults are relative to the parent file's package
        sub_pkg = entry.package
        if sub_pkg is not None and sub_pkg not in ("_global_",) and pkg:
            sub_pkg = f"{pkg}.{sub_pkg}"
        _compose_file(search_path, g, opt, sub_pkg, choices, cli_choices, cfg, depth + 1, is_root=False)


def available_options(search_path: List[str], group: str) -> List[str]:
    opts = set()
    for root in search_path:
        d = os.path.join(root, group)
        if os.path.isdir(d):
            for f in os.listdir(d):
                if f.endswith(".yaml"):
                    opts.add(f[:-5])
    return sorted(opts)


# ---------------------------------------------------------------------------
# CLI override parsing
# ---------------------------------------------------------------------------


def _parse_overrides(
    overrides: Sequence[str], search_path: List[str]
) -> Tuple[Dict[str, str], List[Tuple[str, Any]], List[str]]:
    """Split raw ``key=value`` tokens into (group choices, value sets, deletes)."""
    group_choices: Dict[str, str] = {}
    value_sets: List[Tuple[str, Any]] = []
    deletes: List[str] = []
    for tok in overrides:
        tok = tok.strip()
        if not tok:
            continue
        if tok.startswith("~"):
            deletes.append(tok[1:])
            continue
        if "=" not in tok:
            raise ValueError(f"Override '{tok}' is not of the form key=value")
        key, value = tok.split("=", 1)
        additive = key.startswith("+")
        key = key.lstrip("+")
        if not additive and "." not in key and "@" not in key and _group_exists(search_path, key):
            group_choices[key] = value
        elif "@" in key and "." not in key:
            raise ValueError(f"group@package CLI overrides are not supported: {tok}")
        else:
            value_sets.append((key, yaml_load(value)))
    return group_choices, value_sets, deletes


# ---------------------------------------------------------------------------
# interpolation
# ---------------------------------------------------------------------------

_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


def _resolve_interpolations(cfg: Dict[str, Any]) -> None:
    resolving: set = set()

    def resolve_value(val: Any) -> Any:
        if isinstance(val, str):
            return resolve_str(val)
        if isinstance(val, dict):
            return {k: resolve_value(v) for k, v in val.items()}
        if isinstance(val, list):
            return [resolve_value(v) for v in val]
        return val

    def lookup(ref: str) -> Any:
        if ref in resolving:
            raise ValueError(f"Interpolation cycle at '${{{ref}}}'")
        resolving.add(ref)
        try:
            raw = _get_path(cfg, ref)
            out = resolve_value(raw)
            _set_path(cfg, ref, out)
            return out
        finally:
            resolving.discard(ref)

    def eval_expr(expr: str) -> Any:
        expr = expr.strip()
        if expr.startswith("now:"):
            return datetime.datetime.now().strftime(expr[len("now:"):])
        if expr.startswith("oc.env:"):
            parts = expr[len("oc.env:"):].split(",", 1)
            return os.environ.get(parts[0], parts[1] if len(parts) > 1 else None)
        if expr.startswith("eval:"):
            inner = resolve_str(expr[len("eval:"):])
            return eval(inner, {"__builtins__": {}}, {})  # noqa: S307 — hydra parity
        return lookup(expr)

    def resolve_str(s: str) -> Any:
        m = _INTERP_RE.fullmatch(s)
        if m:  # whole-string reference: keep the referenced type
            return eval_expr(m.group(1))
        out = s
        for _ in range(10):
            if not _INTERP_RE.search(out):
                break
            out = _INTERP_RE.sub(lambda mm: str(eval_expr(mm.group(1))), out)
        return out

    def walk(node: Any, path: str) -> Any:
        if isinstance(node, dict):
            for k in list(node.keys()):
                node[k] = walk(node[k], f"{path}.{k}" if path else k)
            return node
        if isinstance(node, list):
            return [walk(v, path) for v in node]
        if isinstance(node, str) and "${" in node:
            return resolve_value(node)
        return node

    walk(cfg, "")


def _check_missing(cfg: Dict[str, Any], path: str = "", allow: Tuple[str, ...] = ()) -> None:
    if isinstance(cfg, dict):
        for k, v in cfg.items():
            _check_missing(v, f"{path}.{k}" if path else k, allow)
    elif isinstance(cfg, list):
        for i, v in enumerate(cfg):
            _check_missing(v, f"{path}[{i}]", allow)
    elif cfg == MISSING:
        if path in allow:
            return
        raise ValueError(f"Missing mandatory value: {path} (set it with {path}=<VALUE>)")


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def compose(
    config_name: str = "config",
    overrides: Optional[Sequence[str]] = None,
    search_path: Optional[Sequence[str]] = None,
    allow_missing: Tuple[str, ...] = (),
    resolve: bool = True,
) -> dotdict:
    """Compose the config tree, Hydra-style. Returns a :class:`dotdict`."""
    sp = build_search_path(search_path)
    overrides = list(overrides or [])
    cli_choices, value_sets, deletes = _parse_overrides(overrides, sp)

    # two collection passes: the first discovers the exp chain's overrides,
    # the second re-walks with those choices kept so files selected *by* an
    # override also contribute their own overrides.
    choices: Dict[str, str] = {}
    _collect_choices(sp, "", config_name, choices, cli_choices)
    _collect_choices(sp, "", config_name, choices, cli_choices)

    cfg: Dict[str, Any] = {}
    _compose_file(sp, "", config_name, None, choices, cli_choices, cfg)

    for key, value in value_sets:
        _set_path(cfg, key, value)
    for key in deletes:
        _del_path(cfg, key)

    if resolve:
        _resolve_interpolations(cfg)
        _check_missing(cfg, allow=allow_missing)
    return dotdict(cfg)


def to_yaml(cfg) -> str:
    data = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
    return yaml.safe_dump(data, sort_keys=False)


def _split_sweep_values(value: str) -> List[str]:
    """Split a sweep value on TOP-LEVEL commas only.

    Commas inside brackets/braces/parens or quotes are list/dict/str
    literals, not sweep separators — Hydra's grammar makes the same
    distinction (``a=1,2`` sweeps; ``a=[1,2]`` is one list value).
    """
    parts: List[str] = []
    cur: List[str] = []
    depth = 0
    quote: Optional[str] = None
    for ch in value:
        if quote is not None:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    parts.append("".join(cur))
    return parts


def expand_multirun(overrides: Sequence[str]) -> List[List[str]]:
    """Hydra basic-sweeper subset (reference CLI surface: ``sheeprl -m
    exp=... algo.lr=1e-3,1e-4`` via ``@hydra.main`` — hydra 1.3's default
    sweeper): expand comma-separated override values into the cartesian
    product of single-run override lists, preserving override order within
    each job.

    ``exp=a2c,ppo optim.lr=1e-3,1e-4`` -> 4 jobs. Values whose commas sit
    inside brackets or quotes are not swept. Overrides without ``=`` (and
    ``~key`` deletions) pass through unchanged.
    """
    axes: List[List[str]] = []
    for ov in overrides:
        if "=" in ov and not ov.startswith("~"):
            key, value = ov.split("=", 1)
            axes.append([f"{key}={v}" for v in _split_sweep_values(value)])
        else:
            axes.append([ov])
    jobs: List[List[str]] = [[]]
    for axis in axes:
        jobs = [job + [choice] for job in jobs for choice in axis]
    return jobs
