from sheeprl_tpu.config.engine import (
    MISSING,
    SEARCH_PATH_ENV_VAR,
    available_options,
    build_search_path,
    compose,
    to_yaml,
    yaml_load,
)

__all__ = [
    "MISSING",
    "SEARCH_PATH_ENV_VAR",
    "available_options",
    "build_search_path",
    "compose",
    "to_yaml",
    "yaml_load",
]
