"""Fused-kernel subsystem (howto/kernels.md, ROADMAP item 4).

Tiered recurrent-core kernels behind the ``algo.fused_kernels`` knob:
``kernels/reference.py`` is the bitwise flax math (tier ``off``),
``kernels/xla.py`` the padded+fused pure-XLA tier, ``kernels/pallas_tpu.py``
the Pallas TPU kernels, and ``kernels/registry.py`` the build-time tier
resolution + trace-time dispatch + reference-cost accounting hooks.
"""

from sheeprl_tpu.kernels import reference, registry, xla
from sheeprl_tpu.kernels.registry import (
    KERNELS,
    TIERS,
    cost_mode_active,
    default_pad_to,
    flax_gru_cell,
    fused_active,
    hafner_gru_cell,
    hafner_gru_sequence,
    kernel_cost,
    normalize_tier,
    reference_cost_mode,
    resolve_tier,
)

__all__ = [
    "reference",
    "registry",
    "xla",
    "KERNELS",
    "TIERS",
    "cost_mode_active",
    "default_pad_to",
    "flax_gru_cell",
    "fused_active",
    "hafner_gru_cell",
    "hafner_gru_sequence",
    "kernel_cost",
    "normalize_tier",
    "reference_cost_mode",
    "resolve_tier",
]
