"""Reference (tier ``off``) recurrent-cell math — the single source of truth.

This module holds the *exact* gate math the flax modules in
``sheeprl_tpu/models`` and the dreamer agents execute today, extracted so
that (a) the fused tiers in ``xla.py``/``pallas_tpu.py`` have one canonical
program to be tested against, and (b) ``tools/lint_kernels.py`` can forbid
open-coded GRU gate math anywhere under ``algos/`` or ``models/`` outside
this registry.

Two cell families live here:

- ``hafner_cell`` — the Hafner-style LayerNorm-GRU used by the RSSM
  recurrent core (``models.LayerNormGRUCell``; reference dreamerv2
  nets.py:317): one joint Dense over ``[h, x]`` → LayerNorm → gates with
  ``cand = tanh(reset * cand)`` and the update gate biased by −1.
- ``flax_gru_cell`` — flax 0.10 ``nn.GRUCell`` math (DreamerV1's recurrent
  model), with the 6-Dense parameter layout (``ir/iz/in`` with bias,
  ``hr/hz`` without, ``hn`` with).

Every op here is written to be BITWISE what the corresponding flax module
produces (same ``lax.dot_general`` dims, same bias broadcast, the same
``fast_layer_norm`` custom-VJP) — the ``fused_kernels=off`` tier is these
functions, so "off is today's runtime" holds by construction and is
asserted by ``tests/test_models/test_kernels.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from sheeprl_tpu.models.norm import fast_layer_norm

__all__ = ["dense_apply", "hafner_gates", "hafner_cell", "flax_gru_gates", "flax_gru_cell"]


def dense_apply(x: jnp.ndarray, kernel: jnp.ndarray, bias: Optional[jnp.ndarray]) -> jnp.ndarray:
    """``flax.linen.Dense`` forward, bitwise: the same ``dot_general``
    contraction dims and the same reshaped-bias broadcast flax emits."""
    y = jax.lax.dot_general(x, kernel, (((x.ndim - 1,), (0,)), ((), ())))
    if bias is not None:
        y += jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
    return y


def hafner_gates(z: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
    """The Hafner gate block: ``z`` is the (optionally LayerNormed) joint
    projection ``[reset | cand | update]``; returns the new hidden state.
    Op order matches ``models.LayerNormGRUCell.__call__`` exactly."""
    reset, cand, update = jnp.split(z, 3, axis=-1)
    reset = jax.nn.sigmoid(reset)
    cand = jnp.tanh(reset * cand)
    update = jax.nn.sigmoid(update - 1)
    return update * cand + (1 - update) * h


def hafner_cell(
    h: jnp.ndarray,
    x: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    eps: float = 1e-3,
) -> jnp.ndarray:
    """Full reference LayerNorm-GRU step on explicit parameters.

    ``kernel`` is the joint ``[H+X, 3H]`` Dense kernel (h rows first — the
    cell concatenates ``[h, x]``), ``ln_scale``/``ln_bias`` are the
    ``FastLayerNorm`` affine params (``None`` → no LayerNorm, DV1-style).
    """
    inp = jnp.concatenate([h, x], axis=-1)
    z = dense_apply(inp, kernel, bias)
    if ln_scale is not None:
        z = fast_layer_norm(z, ln_scale, ln_bias, float(eps)).astype(
            jnp.promote_types(z.dtype, ln_scale.dtype)
        )
    return hafner_gates(z, h)


def flax_gru_gates(
    ir: jnp.ndarray,
    iz: jnp.ndarray,
    in_: jnp.ndarray,
    hr: jnp.ndarray,
    hz: jnp.ndarray,
    hn: jnp.ndarray,
    h: jnp.ndarray,
) -> jnp.ndarray:
    """flax ``nn.GRUCell`` gate block on the six Dense projections:

        r = σ(ir + hr); z = σ(iz + hz); n = tanh(in + r · hn)
        h' = (1−z)·n + z·h
    """
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    return (1.0 - z) * n + z * h


def flax_gru_cell(h: jnp.ndarray, x: jnp.ndarray, params) -> jnp.ndarray:
    """flax 0.10 ``nn.GRUCell`` math on its native parameter tree
    (``{"ir","iz","in","hr","hz","hn"}``), bitwise the flax module."""

    def dense(inputs, name):
        p = params[name]
        return dense_apply(inputs, p["kernel"], p.get("bias"))

    return flax_gru_gates(
        dense(x, "ir"), dense(x, "iz"), dense(x, "in"),
        dense(h, "hr"), dense(h, "hz"), dense(h, "hn"), h,
    )
