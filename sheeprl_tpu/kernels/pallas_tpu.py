"""Tier ``pallas`` — TPU Pallas kernels for the Hafner LayerNorm-GRU.

Two kernels (see /opt/skills guide + howto/kernels.md):

- **cell**: one fused step — joint matmul (two MXU dots, ``h`` and ``x``
  parts separately so no lane-concat is needed), masked LayerNorm over the
  real lanes, gate block — all in one ``pallas_call`` on the padded
  ``Hp = ceil(H/128)·128`` layout (DV2: 600 → 640, so the 3·H projection
  runs 1920 full lanes instead of 1800 straddled ones).
- **sequence**: the whole ``lax.scan`` time loop fused into ONE kernel:
  ``grid=(T,)`` with the hidden state resident in a VMEM scratch across
  grid steps (verified semantics: scratch persists across iterations,
  ``pl.when(t == 0)`` seeds it from ``h0``), one timestep of ``xs``
  streamed in per step and one row of the trajectory written out.

Both are wrapped in ``jax.custom_vjp`` whose backward is ``jax.vjp`` of
the *padded XLA program* (``kernels.xla``) over the same padded operands —
the ISSUE-sanctioned "backward as the XLA reference autodiff" option: the
fused forward changes the schedule, not the math, so the XLA gradient is
the gradient. Forward parity vs the reference cell and gradient parity vs
reference autodiff are asserted by ``tests/test_models/test_kernels.py``
(CPU via ``interpret=True``).

Input padding: the Pallas tier additionally pads the input width ``X`` to
the lane multiple (extra zero *rows* in the kernel — they contribute
nothing) so every operand lands on full ``(8, 128)`` f32 tiles.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific params; present in the CPU install, harmless if not
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from sheeprl_tpu.kernels import xla

__all__ = ["LANE", "hafner_cell", "hafner_sequence"]

#: TPU vector-lane width — the tile the hidden state is padded to
LANE = 128


def _gate_block(z, h, *, H, Hp, eps, layer_norm, scale, bias):
    """Shared in-kernel epilogue: masked LayerNorm + Hafner gates."""
    if layer_norm:
        n_real = 3.0 * H
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, 3 * Hp), 1)
        mask = ((lane % Hp) < H).astype(jnp.float32)
        mu = jnp.sum(z, axis=-1, keepdims=True) / n_real
        var = jnp.sum(jnp.square(z - mu) * mask, axis=-1, keepdims=True) / n_real
        z = (z - mu) * jax.lax.rsqrt(var + eps)
        z = z * scale + bias
    reset = jax.nn.sigmoid(z[:, :Hp])
    cand = jnp.tanh(reset * z[:, Hp : 2 * Hp])
    update = jax.nn.sigmoid(z[:, 2 * Hp :] - 1.0)
    return update * cand + (1.0 - update) * h


def _cell_kernel(h_ref, x_ref, w_ref, b_ref, s_ref, lb_ref, o_ref, *, H, Hp, eps, layer_norm):
    h = h_ref[...]
    w = w_ref[...]
    # two dots instead of concat([h, x]) @ W: no lane-dim concatenation
    z = jnp.dot(h, w[:Hp], preferred_element_type=jnp.float32)
    z += jnp.dot(x_ref[...], w[Hp:], preferred_element_type=jnp.float32)
    z += b_ref[...]
    o_ref[...] = _gate_block(
        z, h, H=H, Hp=Hp, eps=eps, layer_norm=layer_norm, scale=s_ref[...], bias=lb_ref[...]
    )


def _seq_kernel(
    h0_ref, xs_ref, w_ref, b_ref, s_ref, lb_ref, o_ref, h_scr, *, H, Hp, eps, layer_norm
):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _seed():
        h_scr[...] = h0_ref[...]

    h = h_scr[...]
    w = w_ref[...]
    z = jnp.dot(h, w[:Hp], preferred_element_type=jnp.float32)
    z += jnp.dot(xs_ref[0], w[Hp:], preferred_element_type=jnp.float32)
    z += b_ref[...]
    new_h = _gate_block(
        z, h, H=H, Hp=Hp, eps=eps, layer_norm=layer_norm, scale=s_ref[...], bias=lb_ref[...]
    )
    h_scr[...] = new_h
    o_ref[0] = new_h


def _compiler_params():
    if pltpu is None:  # pragma: no cover
        return None
    # the (T,) grid is a serial recurrence through the VMEM scratch
    return pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))


def _pad_operands(h, x, kernel, bias, ln_scale, ln_bias, *, hidden_size, layer_norm):
    """Real-width operands → full-tile padded layout (H and X both padded;
    dummy ones/zeros LN affine when the cell runs without LayerNorm, so the
    kernel signature is static)."""
    H = int(hidden_size)
    kernel, bias, ln_scale, ln_bias, Hp = xla.pad_hafner_params(
        kernel, bias, ln_scale, ln_bias, hidden_size=H, pad_to=LANE
    )
    X = kernel.shape[0] - Hp
    Xp = xla.round_up(max(X, 1), LANE)
    if Xp != X:
        kernel = jnp.concatenate([kernel[:Hp], xla.pad_axis(kernel[Hp:], 0, Xp)], axis=0)
    x = xla.pad_axis(x, -1, Xp)
    h = xla.pad_axis(h, -1, Hp)
    if bias is None:
        bias = jnp.zeros((3 * Hp,), kernel.dtype)
    if not layer_norm or ln_scale is None:
        ln_scale = jnp.ones((3 * Hp,), kernel.dtype)
        ln_bias = jnp.zeros((3 * Hp,), kernel.dtype)
    return h, x, kernel, bias.reshape(1, -1), ln_scale.reshape(1, -1), ln_bias.reshape(1, -1), Hp


@functools.lru_cache(maxsize=None)
def _make_cell(H: int, Hp: int, eps: float, layer_norm: bool, interpret: bool):
    body = functools.partial(_cell_kernel, H=H, Hp=Hp, eps=eps, layer_norm=layer_norm)

    def impl(h, x, w, b, s, lb):
        call = pl.pallas_call(
            body,
            out_shape=jax.ShapeDtypeStruct(h.shape, jnp.float32),
            interpret=interpret,
            **({} if interpret or pltpu is None else {"compiler_params": _compiler_params()}),
        )
        return call(h, x, w, b, s, lb)

    @jax.custom_vjp
    def cell(h, x, w, b, s, lb):
        return impl(h, x, w, b, s, lb)

    def fwd(h, x, w, b, s, lb):
        return impl(h, x, w, b, s, lb), (h, x, w, b, s, lb)

    def bwd(res, g):
        # gradient of the padded XLA program — same math, XLA's autodiff
        def ref(h, x, w, b, s, lb):
            return xla.hafner_cell_padded(
                h, x, w, b.reshape(-1),
                s.reshape(-1) if layer_norm else None,
                lb.reshape(-1) if layer_norm else None,
                hidden_size=H, padded_size=Hp, eps=eps,
            )

        _, vjp = jax.vjp(ref, *res)
        return vjp(g)

    cell.defvjp(fwd, bwd)
    return cell


@functools.lru_cache(maxsize=None)
def _make_sequence(H: int, Hp: int, eps: float, layer_norm: bool, interpret: bool):
    body = functools.partial(_seq_kernel, H=H, Hp=Hp, eps=eps, layer_norm=layer_norm)

    def impl(h0, xs, w, b, s, lb):
        if pltpu is None:  # pragma: no cover
            raise RuntimeError("pallas TPU support is unavailable in this jax install")
        T, B, Xp = xs.shape
        call = pl.pallas_call(
            body,
            grid=(T,),
            in_specs=[
                pl.BlockSpec((B, Hp), lambda t: (0, 0)),
                pl.BlockSpec((1, B, Xp), lambda t: (t, 0, 0)),
                pl.BlockSpec(w.shape, lambda t: (0, 0)),
                pl.BlockSpec(b.shape, lambda t: (0, 0)),
                pl.BlockSpec(s.shape, lambda t: (0, 0)),
                pl.BlockSpec(lb.shape, lambda t: (0, 0)),
            ],
            out_specs=pl.BlockSpec((1, B, Hp), lambda t: (t, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((T, B, Hp), jnp.float32),
            scratch_shapes=[pltpu.VMEM((B, Hp), jnp.float32)],
            interpret=interpret,
            **({} if interpret or pltpu is None else {"compiler_params": _compiler_params()}),
        )
        return call(h0, xs, w, b, s, lb)

    @jax.custom_vjp
    def seq(h0, xs, w, b, s, lb):
        return impl(h0, xs, w, b, s, lb)

    def fwd(h0, xs, w, b, s, lb):
        return impl(h0, xs, w, b, s, lb), (h0, xs, w, b, s, lb)

    def bwd(res, g):
        def ref(h0, xs, w, b, s, lb):
            return _xla_sequence_padded(
                h0, xs, w, b, s, lb, H=H, Hp=Hp, eps=eps, layer_norm=layer_norm
            )

        _, vjp = jax.vjp(ref, *res)
        return vjp(g)

    seq.defvjp(fwd, bwd)
    return seq


def _xla_sequence_padded(h0, xs, w, b, s, lb, *, H, Hp, eps, layer_norm):
    """Padded-layout XLA twin of the sequence kernel (hoisted input GEMM +
    scan) — the custom-VJP backward program."""
    kh, kx = w[:Hp], w[Hp:]
    zx = jnp.einsum("tbx,xh->tbh", xs, kx) + b

    def bodyfn(h, zx_t):
        z = h @ kh + zx_t
        if layer_norm:
            z = xla.masked_layer_norm(
                z, s.reshape(-1), lb.reshape(-1), eps=eps, hidden_size=H, padded_size=Hp
            )
        reset = jax.nn.sigmoid(z[:, :Hp])
        cand = jnp.tanh(reset * z[:, Hp : 2 * Hp])
        update = jax.nn.sigmoid(z[:, 2 * Hp :] - 1.0)
        new_h = update * cand + (1.0 - update) * h
        return new_h, new_h

    _, hs = jax.lax.scan(bodyfn, h0, zx)
    return hs


def hafner_cell(
    h: jnp.ndarray,
    x: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    eps: float = 1e-3,
    layer_norm: bool = True,
    interpret: bool = False,
) -> jnp.ndarray:
    """One fused LayerNorm-GRU step on real-width operands; pads to tile,
    runs the Pallas cell, slices the real lanes back out."""
    H = int(hidden_size)
    h_p, x_p, w, b, s, lb, Hp = _pad_operands(
        h, x, kernel, bias, ln_scale, ln_bias, hidden_size=H, layer_norm=layer_norm
    )
    cell = _make_cell(H, Hp, float(eps), bool(layer_norm and ln_scale is not None), interpret)
    out = cell(h_p, x_p, w, b, s, lb)
    return out if Hp == H else out[..., :H]


def hafner_sequence(
    h0: jnp.ndarray,
    xs: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    eps: float = 1e-3,
    layer_norm: bool = True,
    interpret: bool = False,
) -> jnp.ndarray:
    """Whole-sequence fused scan: ``xs`` is ``[T, B, X]`` → trajectory
    ``[T, B, H]``, hidden state VMEM-resident across the ``grid=(T,)``."""
    H = int(hidden_size)
    h_p, xs_p, w, b, s, lb, Hp = _pad_operands(
        h0, xs, kernel, bias, ln_scale, ln_bias, hidden_size=H, layer_norm=layer_norm
    )
    seq = _make_sequence(H, Hp, float(eps), bool(layer_norm and ln_scale is not None), interpret)
    out = seq(h_p, xs_p, w, b, s, lb)
    return out if Hp == H else out[..., :H]
