"""Tiered dispatch registry for the fused-kernel subsystem.

One knob — ``algo.fused_kernels`` — resolved ONCE at agent-build time by
``resolve_tier`` into a tier string baked into the flax modules:

- ``off``    — the reference flax path (``kernels/reference.py``), bitwise
  today's runtime. Also what ``auto`` means on hosts with no fused win.
- ``xla``    — padded + fused pure-XLA cells (``kernels/xla.py``); runs
  everywhere, ``pad_to`` defaults to the 128-lane tile on TPU and 1 (no
  padding, bitwise reference) elsewhere.
- ``pallas`` — the Pallas TPU kernels (``kernels/pallas_tpu.py``). On a
  non-TPU backend this request auto-degrades to ``xla`` with a logged
  notice and a ``kernel_tier_degraded`` telemetry count (tests exercise
  the Pallas tier on CPU explicitly via ``interpret=True``).
- ``auto``   — ``pallas`` on TPU, ``xla`` elsewhere.

The registry also owns two cross-cutting facilities:

- ``reference_cost_mode()`` — a contextvar the dispatchers check at TRACE
  time: inside it every fused cell lowers as the reference program. PR-8's
  ``register_train_cost`` retraces the train step under this mode, so
  roofline/MFU accounting always prices the *reference* FLOPs/bytes — a
  fused (padded) program cannot inflate its own MFU denominator.
- ``fused_active()`` — whether any non-``off`` tier was resolved in this
  process, so cost accounting knows a retrace is needed at all.

Adding a kernel (howto/kernels.md): put the reference math in
``reference.py``, the fused tiers in ``xla.py``/``pallas_tpu.py``, add a
``KERNELS`` row + an analytic ``kernel_cost`` entry here, dispatch from
the owning flax module through this registry, and extend the parity suite.
``tools/lint_kernels.py`` enforces that gate math lives nowhere else.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from sheeprl_tpu.kernels import reference, xla

_LOGGER = logging.getLogger(__name__)

TIERS = ("off", "xla", "pallas")

#: kernel family -> implemented tiers (beyond the always-available ``off``)
KERNELS: Dict[str, Dict[str, Any]] = {
    # the RSSM recurrent core (models.LayerNormGRUCell): DV2/P2E-DV2 at
    # H=600, DV3 shares the module but keeps fused_kernels=off for now
    "hafner_ln_gru": {"tiers": ("off", "xla", "pallas")},
    # DreamerV1's flax nn.GRUCell math: no Pallas kernel yet — a ``pallas``
    # request degrades to ``xla`` with a notice
    "flax_gru": {"tiers": ("off", "xla")},
}

_REFERENCE_COST = contextvars.ContextVar("sheeprl_kernels_reference_cost", default=False)
_ACTIVE_FUSED = set()


@contextlib.contextmanager
def reference_cost_mode():
    """While active (including at trace time inside a fresh ``jax.jit``),
    every registry dispatch takes the reference path regardless of tier."""
    token = _REFERENCE_COST.set(True)
    try:
        yield
    finally:
        _REFERENCE_COST.reset(token)


def cost_mode_active() -> bool:
    return bool(_REFERENCE_COST.get())


def fused_active() -> bool:
    """True when any agent in this process was built with a fused tier."""
    return bool(_ACTIVE_FUSED)


def normalize_tier(value: Any) -> str:
    """Config values arrive as strings or YAML booleans (bare ``off`` in
    YAML 1.1 parses as ``False``; ``on``/``True`` means ``auto``)."""
    if value is None or value is False:
        return "off"
    if value is True:
        return "auto"
    tier = str(value).strip().lower()
    if tier in ("", "0", "false", "none", "no"):
        return "off"
    if tier in ("1", "true", "yes", "on"):
        return "auto"
    return tier


def resolve_tier(requested: Any, *, family: str = "hafner_ln_gru") -> str:
    """Resolve the ``algo.fused_kernels`` knob to a concrete tier for one
    kernel family on the current backend (called at agent-build time)."""
    tier = normalize_tier(requested)
    if tier == "auto":
        tier = "pallas" if jax.default_backend() == "tpu" else "xla"
    if tier not in TIERS:
        raise ValueError(
            f"algo.fused_kernels={requested!r}: expected one of {TIERS + ('auto',)}"
        )
    if tier == "pallas" and jax.default_backend() != "tpu":
        _LOGGER.warning(
            "fused_kernels=pallas requested on backend=%s: degrading to the "
            "padded-XLA tier (the Pallas kernels target TPU; CPU parity runs "
            "use interpret mode in the test suite)",
            jax.default_backend(),
        )
        _count_degrade()
        tier = "xla"
    if tier == "pallas" and "pallas" not in KERNELS[family]["tiers"]:
        _LOGGER.warning(
            "fused_kernels=pallas: kernel family %r has no Pallas tier yet — "
            "degrading to xla",
            family,
        )
        _count_degrade()
        tier = "xla"
    if tier != "off":
        _ACTIVE_FUSED.add(tier)
    return tier


def _count_degrade() -> None:
    # late import: obs.counters is optional at import time and obs imports us
    try:
        from sheeprl_tpu.obs.counters import add_kernel_tier_degraded

        add_kernel_tier_degraded()
    except Exception:  # pragma: no cover - counters not initialised
        pass


def default_pad_to(tier: str) -> int:
    """The xla tier pads to the MXU tile only where tiling exists: on CPU
    ``pad_to=1`` keeps the fused cell bitwise the reference op sequence."""
    if tier == "xla" and jax.default_backend() != "tpu":
        return 1
    return 128


# ---------------------------------------------------------------------------
# dispatchers — the only entrypoints the flax modules call
# ---------------------------------------------------------------------------


def hafner_gru_cell(
    h: jnp.ndarray,
    x: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    eps: float,
    tier: str,
    pad_to: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """One LayerNorm-GRU step through the resolved tier."""
    if tier == "off" or cost_mode_active():
        return reference.hafner_cell(h, x, kernel, bias, ln_scale, ln_bias, eps=eps)
    if tier == "xla":
        return xla.hafner_cell_fused(
            h, x, kernel, bias, ln_scale, ln_bias,
            hidden_size=hidden_size, eps=eps,
            pad_to=default_pad_to(tier) if pad_to is None else pad_to,
        )
    if tier == "pallas":
        from sheeprl_tpu.kernels import pallas_tpu

        return pallas_tpu.hafner_cell(
            h, x, kernel, bias, ln_scale, ln_bias,
            hidden_size=hidden_size, eps=eps,
            layer_norm=ln_scale is not None, interpret=interpret,
        )
    raise ValueError(f"unknown kernel tier {tier!r}")


def hafner_gru_sequence(
    h0: jnp.ndarray,
    xs: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    eps: float,
    tier: str,
    pad_to: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Whole-sequence LayerNorm-GRU (``xs`` known up front): the fused
    scan with the hoisted input GEMM (xla) or the VMEM-resident Pallas
    scan. ``off`` runs the reference cell under ``lax.scan``."""
    if tier == "off" or cost_mode_active():
        def body(h, x_t):
            new_h = reference.hafner_cell(h, x_t, kernel, bias, ln_scale, ln_bias, eps=eps)
            return new_h, new_h

        _, hs = jax.lax.scan(body, h0, xs)
        return hs
    if tier == "xla":
        return xla.hafner_sequence_fused(
            h0, xs, kernel, bias, ln_scale, ln_bias,
            hidden_size=hidden_size, eps=eps,
            pad_to=default_pad_to(tier) if pad_to is None else pad_to,
        )
    if tier == "pallas":
        from sheeprl_tpu.kernels import pallas_tpu

        return pallas_tpu.hafner_sequence(
            h0, xs, kernel, bias, ln_scale, ln_bias,
            hidden_size=hidden_size, eps=eps,
            layer_norm=ln_scale is not None, interpret=interpret,
        )
    raise ValueError(f"unknown kernel tier {tier!r}")


def flax_gru_cell(
    h: jnp.ndarray,
    x: jnp.ndarray,
    params,
    *,
    hidden_size: int,
    tier: str,
    pad_to: Optional[int] = None,
) -> jnp.ndarray:
    """One flax-convention GRU step through the resolved tier (``pallas``
    resolves to ``xla`` for this family at build time)."""
    if tier == "off" or cost_mode_active():
        return reference.flax_gru_cell(h, x, params)
    return xla.flax_gru_cell_fused(
        h, x, params,
        hidden_size=hidden_size,
        pad_to=default_pad_to("xla") if pad_to is None else pad_to,
    )


# ---------------------------------------------------------------------------
# analytic per-kernel cost specs (reference widths — never the padded ones)
# ---------------------------------------------------------------------------


def kernel_cost(
    family: str,
    *,
    batch: int,
    hidden_size: int,
    input_size: int,
    seq_len: int = 1,
    layer_norm: bool = True,
) -> Dict[str, float]:
    """Reference FLOPs/bytes for one forward of a kernel family at REAL
    (unpadded) widths — the denominator bench_kernels.py and the roofline
    use, so padding can never inflate a utilization number."""
    B, H, X, T = int(batch), int(hidden_size), int(input_size), int(seq_len)
    if family not in KERNELS:
        raise KeyError(f"unknown kernel family {family!r}")
    steps = B * T
    matmul = 2.0 * steps * (H + X) * (3 * H)
    ln = (8.0 * steps * 3 * H) if (layer_norm and family == "hafner_ln_gru") else 0.0
    gates = 10.0 * steps * H
    flops = matmul + ln + gates
    # params once + activations per step, f32
    param_bytes = 4.0 * ((H + X) * 3 * H + 3 * H * (3 if layer_norm else 1))
    act_bytes = 4.0 * steps * (H + X + H)
    return {"flops": flops, "bytes": param_bytes + act_bytes}
