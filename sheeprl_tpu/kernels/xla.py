"""Tier ``xla`` — padded + fused recurrent cells in pure XLA.

Runs everywhere (including the tier-1 CPU lane). Two ideas:

- **Pad-to-tile**: the DV2 RSSM hidden width (600) straddles the TPU's
  128-lane tile; padding ``H → ceil(H/128)·128`` (640) lets every matmul
  and elementwise op land on full tiles. Padding is pure zero-extension of
  the parameters *inside* the differentiated program, so gradients flow
  back through the padding ops and slice themselves to the real blocks —
  no separate unpad bookkeeping. On CPU ``pad_to=1`` short-circuits to the
  unpadded shapes and the op sequence is bitwise the reference cell
  (asserted in tests/test_models/test_kernels.py).
- **Fuse / hoist**: the cell runs as one joint projection + one gate
  block; the sequence form additionally hoists the input projection
  ``xs @ W_x`` out of the ``lax.scan`` into a single ``[T·B, X]`` GEMM
  (the cuDNN-RNN trick), shrinking the serial per-step matmul from
  ``[B, H+X]@[H+X, 3H]`` to ``[B, H]@[H, 3H]``. The sequence form applies
  when the whole input sequence is known up front (bench, embeddings
  precomputed); the production RSSM scan feeds the cell per step because
  ``x_t`` depends on the previous posterior.

Padding invariants (why masking cannot leak): padded kernel columns, bias
lanes, and LayerNorm scale/bias lanes are zero, so padded pre-activation
lanes are exactly 0 and LayerNorm statistics are taken over the real
lanes only (explicit mask in the variance); a zero-initialised padded
hidden lane stays exactly 0 through the gate block (``cand = tanh(σ(0)·0)
= 0``), so real lanes never see padding garbage. Verified at widths
600/599/128/1 by the parity suite.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.kernels import reference
from sheeprl_tpu.models.norm import fast_layer_norm

__all__ = [
    "round_up",
    "pad_axis",
    "pad_hafner_params",
    "pad_flax_gru_params",
    "masked_layer_norm",
    "hafner_cell_fused",
    "hafner_sequence_fused",
    "flax_gru_cell_fused",
]


def round_up(n: int, multiple: int) -> int:
    return ((int(n) + multiple - 1) // multiple) * multiple


def pad_axis(a: jnp.ndarray, axis: int, new_size: int) -> jnp.ndarray:
    """Zero-pad one axis up to ``new_size`` (no-op when already there)."""
    if a.shape[axis] == new_size:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, new_size - a.shape[axis])
    return jnp.pad(a, widths)


def pad_hafner_params(
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    pad_to: int,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], Optional[jnp.ndarray], Optional[jnp.ndarray], int]:
    """Zero-extend the joint ``[H+X, 3H]`` Hafner parameters to the padded
    layout ``[Hp+X, 3Hp]`` (gate ``g``'s real block lands at rows ``:H`` /
    ``Hp:`` and columns ``g·Hp : g·Hp+H``). Returns ``(kernel, bias,
    ln_scale, ln_bias, Hp)``; everything passes through untouched when
    ``Hp == H``."""
    H = int(hidden_size)
    Hp = round_up(H, pad_to)
    if Hp == H:
        return kernel, bias, ln_scale, ln_bias, H
    X = kernel.shape[0] - H

    def pad_cols(v):
        # [.., 3H] -> [.., 3Hp] with each gate block re-based at g*Hp
        parts = jnp.split(v, 3, axis=-1)
        return jnp.concatenate([pad_axis(p, -1, Hp) for p in parts], axis=-1)

    kh = pad_axis(pad_cols(kernel[:H]), 0, Hp)  # [Hp, 3Hp]
    kx = pad_cols(kernel[H : H + X])  # [X, 3Hp]
    kernel_p = jnp.concatenate([kh, kx], axis=0)  # [Hp+X, 3Hp]
    bias_p = pad_cols(bias) if bias is not None else None
    scale_p = pad_cols(ln_scale) if ln_scale is not None else None
    lnb_p = pad_cols(ln_bias) if ln_bias is not None else None
    return kernel_p, bias_p, scale_p, lnb_p, Hp


def masked_layer_norm(
    z: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    *,
    eps: float,
    hidden_size: int,
    padded_size: int,
) -> jnp.ndarray:
    """LayerNorm over the REAL lanes of a gate-padded ``[.., 3·Hp]`` vector.

    Padded pre-activation lanes are exactly 0 by the padding invariant, so
    the mean needs no mask (sum over all lanes == sum over real lanes); the
    variance masks explicitly because ``(0 − μ)²`` is not 0. Padded
    scale/bias lanes are 0, so padded outputs stay exactly 0. Reduces to
    ``fast_layer_norm`` semantics when ``padded_size == hidden_size``.
    """
    H, Hp = int(hidden_size), int(padded_size)
    n_real = 3.0 * H
    zf = z.astype(jnp.float32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (3 * Hp,), 0)
    mask = ((lane % Hp) < H).astype(jnp.float32)
    mu = jnp.sum(zf, axis=-1, keepdims=True) / n_real
    var = jnp.sum(jnp.square(zf - mu) * mask, axis=-1, keepdims=True) / n_real
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (zf - mu) * rstd
    y = xhat * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(jnp.promote_types(z.dtype, scale.dtype))


def hafner_cell_fused(
    h: jnp.ndarray,
    x: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    eps: float = 1e-3,
    pad_to: int = 1,
) -> jnp.ndarray:
    """One fused LayerNorm-GRU step on (possibly unpadded) real-width
    inputs: pads parameters + hidden state, runs the padded cell, slices
    the real lanes back out. With ``pad_to=1`` this is bitwise
    ``reference.hafner_cell`` (same dot dims, same ``fast_layer_norm``)."""
    H = int(hidden_size)
    kernel, bias, ln_scale, ln_bias, Hp = pad_hafner_params(
        kernel, bias, ln_scale, ln_bias, hidden_size=H, pad_to=pad_to
    )
    hp = pad_axis(h, -1, Hp)
    new_h = hafner_cell_padded(
        hp, x, kernel, bias, ln_scale, ln_bias, hidden_size=H, padded_size=Hp, eps=eps
    )
    return new_h if Hp == H else new_h[..., :H]


def hafner_cell_padded(
    h: jnp.ndarray,
    x: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    padded_size: int,
    eps: float,
) -> jnp.ndarray:
    """The padded-layout cell body (also the `custom_vjp` backward program
    for the Pallas tier: ``jax.vjp`` of this function IS the fused
    kernel's gradient). All inputs already in the ``Hp`` layout."""
    H, Hp = int(hidden_size), int(padded_size)
    inp = jnp.concatenate([h, x], axis=-1)
    z = reference.dense_apply(inp, kernel, bias)
    if ln_scale is not None:
        if Hp == H:
            z = fast_layer_norm(z, ln_scale, ln_bias, float(eps)).astype(
                jnp.promote_types(z.dtype, ln_scale.dtype)
            )
        else:
            z = masked_layer_norm(
                z, ln_scale, ln_bias, eps=float(eps), hidden_size=H, padded_size=Hp
            )
    return reference.hafner_gates(z, h)


def hafner_sequence_fused(
    h0: jnp.ndarray,
    xs: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    ln_scale: Optional[jnp.ndarray],
    ln_bias: Optional[jnp.ndarray],
    *,
    hidden_size: int,
    eps: float = 1e-3,
    pad_to: int = 1,
) -> jnp.ndarray:
    """Whole-sequence LayerNorm-GRU: ``xs`` is ``[T, B, X]``, returns the
    hidden trajectory ``[T, B, H]``. The input projection runs as ONE GEMM
    outside the scan; only the ``[B, Hp]@[Hp, 3Hp]`` recurrent matmul and
    the gate block stay serial."""
    H = int(hidden_size)
    kernel, bias, ln_scale, ln_bias, Hp = pad_hafner_params(
        kernel, bias, ln_scale, ln_bias, hidden_size=H, pad_to=pad_to
    )
    kh, kx = kernel[:Hp], kernel[Hp:]
    # hoisted input projection (+ bias, so the scan body adds nothing twice)
    zx = reference.dense_apply(xs, kx, bias)  # [T, B, 3Hp]
    hp = pad_axis(h0, -1, Hp)

    def body(h, zx_t):
        z = jax.lax.dot_general(h, kh, (((h.ndim - 1,), (0,)), ((), ()))) + zx_t
        if ln_scale is not None:
            z = masked_layer_norm(
                z, ln_scale, ln_bias, eps=float(eps), hidden_size=H, padded_size=Hp
            )
        new_h = reference.hafner_gates(z, h)
        return new_h, new_h

    _, hs = jax.lax.scan(body, hp, zx)
    return hs if Hp == H else hs[..., :H]


def pad_flax_gru_params(params, *, hidden_size: int, pad_to: int):
    """Pack the flax ``ir/iz/in | hr/hz/hn`` six-Dense tree into two padded
    joint kernels: ``Wi [X, 3Hp]`` (+ joint input bias ``[3Hp]``) and
    ``Wh [Hp, 3Hp]`` (+ the ``hn`` bias ``[Hp]``). Gate order r|z|n."""
    H = int(hidden_size)
    Hp = round_up(H, pad_to)

    def padded(name):
        k = pad_axis(params[name]["kernel"], -1, Hp)
        b = params[name].get("bias")
        return k, (pad_axis(b, -1, Hp) if b is not None else jnp.zeros((Hp,), k.dtype))

    kir, bir = padded("ir")
    kiz, biz = padded("iz")
    kin, bin_ = padded("in")
    khr, _ = padded("hr")
    khz, _ = padded("hz")
    khn, bhn = padded("hn")
    wi = jnp.concatenate([kir, kiz, kin], axis=-1)
    bi = jnp.concatenate([bir, biz, bin_], axis=-1)
    wh = jnp.concatenate([pad_axis(k, 0, Hp) for k in (khr, khz, khn)], axis=-1)
    return wi, bi, wh, bhn, Hp


def flax_gru_cell_fused(
    h: jnp.ndarray,
    x: jnp.ndarray,
    params,
    *,
    hidden_size: int,
    pad_to: int = 1,
) -> jnp.ndarray:
    """Fused flax-GRU step: the six Denses collapse into one ``[B, X]@[X,
    3Hp]`` input GEMM and one ``[B, Hp]@[Hp, 3Hp]`` recurrent GEMM, then
    the gate block. Padded hidden lanes stay exactly 0 (``n = tanh(0 +
    σ(0)·0) = 0`` and ``(1−z)·0 + z·0 = 0``). Numerically equivalent — not
    bitwise — to the reference (different GEMM grouping); tolerance-tested.
    """
    H = int(hidden_size)
    wi, bi, wh, bhn, Hp = pad_flax_gru_params(params, hidden_size=H, pad_to=pad_to)
    hp = pad_axis(h, -1, Hp)
    zi = reference.dense_apply(x, wi, bi)
    zh = reference.dense_apply(hp, wh, None)
    r = jax.nn.sigmoid(zi[..., :Hp] + zh[..., :Hp])
    z = jax.nn.sigmoid(zi[..., Hp : 2 * Hp] + zh[..., Hp : 2 * Hp])
    n = jnp.tanh(zi[..., 2 * Hp :] + r * (zh[..., 2 * Hp :] + bhn))
    new_h = (1.0 - z) * n + z * hp
    return new_h if Hp == H else new_h[..., :H]
