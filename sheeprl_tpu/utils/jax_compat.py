"""Version portability shims for the jax APIs this framework leans on.

The framework targets the current jax API surface; two symbols it uses moved
between releases and break older pinned containers:

- ``jax.shard_map`` graduated from ``jax.experimental.shard_map`` (and its
  replication-check kwarg was renamed ``check_rep`` → ``check_vma`` along the
  way).
- ``jax.lax.axis_size`` did not exist before 0.5; under an active named-axis
  trace the size is available from the axis environment.

Every call site imports from here instead of feature-testing jax inline, so
the framework runs unmodified on both sides of the rename.

``auto`` marks mesh axes the body does NOT reduce over manually: those axes
stay under GSPMD control, so an enclosing ``jit(..., in_shardings=...)`` can
partition the body's tensor work over them (the pjit/PartitionSpec pattern)
while the remaining axes keep their hand-written per-shard collectives. This
is how the ``'model'`` parameter axis composes with the manual ``'data'``
gradient pmean without rewriting the train steps.
"""

from __future__ import annotations

from typing import Any, FrozenSet

import jax

__all__ = ["shard_map", "axis_size"]


if hasattr(jax, "shard_map"):

    def shard_map(
        f,
        *,
        mesh,
        in_specs,
        out_specs,
        check_vma: bool = True,
        auto: FrozenSet[str] = frozenset(),
    ):
        kwargs = {"auto": auto} if auto else {}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma, **kwargs
        )

else:  # pre-graduation jax: experimental module, check_rep kwarg

    def shard_map(
        f,
        *,
        mesh,
        in_specs,
        out_specs,
        check_vma: bool = True,
        auto: FrozenSet[str] = frozenset(),
    ):
        from jax.experimental.shard_map import shard_map as _shard_map

        kwargs = {"auto": auto} if auto else {}
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma, **kwargs
        )


if hasattr(jax.lax, "axis_size"):

    def axis_size(axis_name: Any) -> int:
        return jax.lax.axis_size(axis_name)

else:

    def axis_size(axis_name: Any) -> int:
        """Size of a bound mesh axis (static python int, like jax.lax.axis_size)."""
        from jax._src import core as _core

        return _core.trace_ctx.axis_env.axis_size(axis_name)
