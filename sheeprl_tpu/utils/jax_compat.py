"""Version portability shims for the jax APIs this framework leans on.

The framework targets the current jax API surface; two symbols it uses moved
between releases and break older pinned containers:

- ``jax.shard_map`` graduated from ``jax.experimental.shard_map`` (and its
  replication-check kwarg was renamed ``check_rep`` → ``check_vma`` along the
  way).
- ``jax.lax.axis_size`` did not exist before 0.5; under an active named-axis
  trace the size is available from the axis environment.

Every call site imports from here instead of feature-testing jax inline, so
the framework runs unmodified on both sides of the rename.
"""

from __future__ import annotations

from typing import Any

import jax

__all__ = ["shard_map", "axis_size"]


if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )

else:  # pre-graduation jax: experimental module, check_rep kwarg

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )


if hasattr(jax.lax, "axis_size"):

    def axis_size(axis_name: Any) -> int:
        return jax.lax.axis_size(axis_name)

else:

    def axis_size(axis_name: Any) -> int:
        """Size of a bound mesh axis (static python int, like jax.lax.axis_size)."""
        from jax._src import core as _core

        return _core.trace_ctx.axis_env.axis_size(axis_name)
