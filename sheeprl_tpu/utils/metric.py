"""Metric aggregation.

TPU-native re-design of the reference observability pieces
(``sheeprl/utils/metric.py``: MetricAggregator :17-143,
RankIndependentMetricAggregator :146-195; torchmetrics Mean/Sum/Max/Min
metrics built from config, ``configs/metric/default.yaml``).

Metrics here are tiny host-side accumulators over python floats / numpy
scalars — deliberately *not* jax arrays, so updating them never inserts a
device sync into the train loop; callers pass values they already pulled from
the device (usually once per `log_every` window). ``sync_on_compute`` uses
``jax.experimental.multihost_utils`` process-level collectives when running
multi-host, mirroring the reference's torchmetrics distributed sync.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import numpy as np


# Run-health hook (obs/health.py NonFiniteGuard): when set, every value
# entering an aggregator is offered to the guard — the one chokepoint all
# algorithms log losses through, so NaN/inf detection needs no per-algo code.
_VALUE_GUARD = None


def set_value_guard(guard) -> None:
    """Install (or with ``None`` remove) the metric value guard."""
    global _VALUE_GUARD
    _VALUE_GUARD = guard


def _to_scalar(value: Any) -> float:
    """Accept python numbers, numpy scalars, and (possibly device) jax arrays."""
    if hasattr(value, "item"):
        return float(np.asarray(value).item())
    return float(value)


def _process_sum(values: np.ndarray) -> np.ndarray:
    """Sum an array across processes (no-op single-process)."""
    if jax.process_count() == 1:
        return values
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(values)).sum(axis=0)


class Metric:
    """Base accumulator. Subclasses define how values fold together."""

    def __init__(self, sync_on_compute: bool = False):
        self.sync_on_compute = sync_on_compute
        self.reset()

    def update(self, value: Any, weight: float = 1.0) -> None:
        raise NotImplementedError

    def compute(self) -> float:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class MeanMetric(Metric):
    def reset(self) -> None:
        self._sum = 0.0
        self._count = 0.0

    def update(self, value: Any, weight: float = 1.0) -> None:
        self._sum += _to_scalar(value) * weight
        self._count += weight

    def compute(self) -> float:
        total, count = self._sum, self._count
        if self.sync_on_compute:
            synced = _process_sum(np.array([total, count]))
            total, count = float(synced[0]), float(synced[1])
        return total / count if count else float("nan")


class SumMetric(Metric):
    def reset(self) -> None:
        self._sum = 0.0

    def update(self, value: Any, weight: float = 1.0) -> None:
        self._sum += _to_scalar(value)

    def compute(self) -> float:
        if self.sync_on_compute:
            return float(_process_sum(np.array([self._sum]))[0])
        return self._sum


class _ExtremumMetric(Metric):
    _fold = staticmethod(max)
    _empty = float("nan")

    def reset(self) -> None:
        self._value: Optional[float] = None

    def update(self, value: Any, weight: float = 1.0) -> None:
        v = _to_scalar(value)
        self._value = v if self._value is None else self._fold(self._value, v)

    def compute(self) -> float:
        value = self._empty if self._value is None else self._value
        if self.sync_on_compute and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            gathered = np.asarray(multihost_utils.process_allgather(np.array([value])))
            finite = gathered[np.isfinite(gathered)]
            return float(self._fold(finite.tolist())) if finite.size else self._empty
        return value


class MaxMetric(_ExtremumMetric):
    _fold = staticmethod(max)


class MinMetric(_ExtremumMetric):
    _fold = staticmethod(min)


class LastValueMetric(Metric):
    """Keeps only the most recent value (useful for schedules/counters)."""

    def reset(self) -> None:
        self._value = float("nan")

    def update(self, value: Any, weight: float = 1.0) -> None:
        self._value = _to_scalar(value)

    def compute(self) -> float:
        return self._value


class MetricAggregator:
    """Name→Metric dict driven by config (reference metric.py:17-143).

    ``update`` on a missing key raises only when ``raise_on_missing`` — the CLI
    prunes unwanted keys at startup, so silent-skip is the normal mode.
    ``compute`` drops NaN values, as the reference does (metric.py:138-142).
    """

    disabled: bool = False

    def __init__(self, metrics: Optional[Dict[str, Metric]] = None, raise_on_missing: bool = False):
        self.metrics: Dict[str, Metric] = dict(metrics or {})
        self._raise_on_missing = raise_on_missing

    def add(self, name: str, metric: Metric) -> None:
        if name in self.metrics:
            raise ValueError(f"Metric '{name}' already present in the aggregator")
        self.metrics[name] = metric

    def pop(self, name: str) -> None:
        if name not in self.metrics and self._raise_on_missing:
            raise KeyError(f"Metric '{name}' not present in the aggregator")
        self.metrics.pop(name, None)

    def update(self, name: str, value: Any, weight: float = 1.0) -> None:
        if self.disabled:
            return
        metric = self.metrics.get(name)
        if metric is None:
            if self._raise_on_missing:
                raise KeyError(f"Metric '{name}' not present in the aggregator")
            return
        if _VALUE_GUARD is not None:
            _VALUE_GUARD(name, value)
        metric.update(value, weight)

    def reset(self) -> None:
        for metric in self.metrics.values():
            metric.reset()

    def compute(self) -> Dict[str, float]:
        if self.disabled:
            return {}
        out: Dict[str, float] = {}
        for name, metric in self.metrics.items():
            try:
                value = metric.compute()
            except Exception:
                continue
            if not (isinstance(value, float) and math.isnan(value)):
                out[name] = value
        return out

    def keys(self):
        return self.metrics.keys()

    def __contains__(self, name: str) -> bool:
        return name in self.metrics


class RankIndependentMetricAggregator:
    """Per-process values gathered without reduction (reference metric.py:146-195)."""

    def __init__(self, metrics: Union[Sequence[str], Dict[str, Metric]]):
        if not isinstance(metrics, dict):
            metrics = {name: MeanMetric(sync_on_compute=False) for name in metrics}
        self._aggregator = MetricAggregator(metrics)

    def update(self, name: str, value: Any) -> None:
        self._aggregator.update(name, value)

    def compute(self) -> Dict[str, List[float]]:
        local = self._aggregator.compute()
        if jax.process_count() == 1:
            return {k: [v] for k, v in local.items()}
        from jax.experimental import multihost_utils

        keys = sorted(local.keys())
        values = np.array([local[k] for k in keys])
        gathered = np.asarray(multihost_utils.process_allgather(values))
        return {k: gathered[:, i].tolist() for i, k in enumerate(keys)}

    def reset(self) -> None:
        self._aggregator.reset()
