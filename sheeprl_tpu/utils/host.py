"""Host-side parameter mirroring for the acting path.

Environment interaction is latency-bound: one jitted policy call per env
step. When the mesh is a (possibly remote-attached) accelerator, dispatching
that call to the mesh costs a full round trip per step, which dominates
wall-clock (SURVEY §5.8 — players live on CPU hosts feeding the trainer
mesh). :class:`HostParamMirror` keeps a CPU copy of the acting parameters,
refreshed once per update as a **single packed transfer**: the pytree is
raveled on the mesh (one jitted concat) so the snapshot crosses the wire as
one array instead of one round trip per leaf, then unraveled on the host.

Usage::

    mirror = HostParamMirror(params, enabled=fabric.on_accelerator)
    play_params = mirror(params)          # CPU tree (or `params` if disabled)
    ...
    play_params = mirror(new_params)      # refresh after each update
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


class HostParamMirror:
    @staticmethod
    def enabled_for(fabric, cfg) -> bool:
        """The one enable rule shared by every algorithm: host acting is on
        unless ``algo.player_on_host=False``, and only matters when the mesh
        runs on an accelerator."""
        return bool(cfg.algo.get("player_on_host", True)) and fabric.on_accelerator

    @classmethod
    def from_cfg(cls, example_tree: Any, fabric, cfg) -> "HostParamMirror":
        """The one construction rule: enable per :meth:`enabled_for`,
        refresh cadence from ``algo.player_on_host_refresh_every``."""
        return cls(
            example_tree,
            enabled=cls.enabled_for(fabric, cfg),
            refresh_every=cfg.algo.get("player_on_host_refresh_every", 1),
        )

    def __init__(self, example_tree: Any, enabled: bool = True, refresh_every: int = 1):
        self.enabled = bool(enabled)
        # refreshing costs one full-model transfer; a cadence > 1 lets the
        # player act on a snapshot stale by up to refresh_every-1 updates
        # (algo.player_on_host_refresh_every)
        self.refresh_every = max(int(refresh_every or 1), 1)
        self._calls = 0
        self._cache: Any = None
        if self.enabled:
            from jax.flatten_util import ravel_pytree

            self._host = jax.devices("cpu")[0]
            _, self._unravel = ravel_pytree(jax.device_get(example_tree))
            self._pack = jax.jit(lambda p: ravel_pytree(p)[0])

    def __call__(self, tree: Any) -> Any:
        if not self.enabled:
            return tree
        if self._cache is None or self._calls % self.refresh_every == 0:
            # async D2H: device_put of the packed vector to the host enqueues
            # the transfer without blocking (over a remote-attached TPU the
            # blocking pull costs a full tunnel round trip); the unravel runs
            # on the CPU backend and only waits when the player first reads
            # the params, by which time env bookkeeping has overlapped it
            flat = jax.device_put(self._pack(tree), self._host)
            self._cache = self._unravel(flat)
        self._calls += 1
        return self._cache

    def put_key(self, key: jax.Array) -> jax.Array:
        """Commit a PRNG key next to the mirrored params."""
        return jax.device_put(key, self._host) if self.enabled else key
