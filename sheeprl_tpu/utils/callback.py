"""Checkpoint callback.

Reference behavior (``sheeprl/utils/callback.py:10-92``): dispatched via
``fabric.call("on_checkpoint_{coupled|player|trainer}")``; optionally embeds
the replay-buffer state with the last stored terminal flags forced to 1 so
the in-progress episode terminates cleanly on restore (callback.py:32-40,
59-64 — applied to ``dones`` AND the gymnasium five-tuple ``terminated`` /
``truncated`` keys, so both termination paths end restored episodes).

Persistence itself is the :mod:`sheeprl_tpu.ckpt` subsystem's job: the hooks
snapshot the buffer state on the step path and hand everything to the run's
:class:`~sheeprl_tpu.ckpt.manager.CheckpointManager` (async double-buffered
writes, atomic manifest layout, keep-policy GC on the writer thread — which
is also where the old ``_prune`` moved, so GC can no longer race an
in-flight async write).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class CheckpointCallback:
    """Saves `state` (a pytree of arrays + counters) and optionally buffers.

    ``keep_last`` (fabric-config knob) overrides the manager's
    ``checkpoint.keep_last`` policy when set.
    """

    def __init__(self, keep_last: Optional[int] = None):
        self.keep_last = keep_last

    # -- buffer embedding ------------------------------------------------

    @staticmethod
    def _force_terminal(state: Dict[str, Any]) -> Dict[str, Any]:
        """Force the last stored step of a ReplayBuffer-style state dict to be
        terminal on every termination key present (reference :32-40)."""
        buf = state.get("buffer")
        if isinstance(buf, dict):
            pos = int(np.asarray(state.get("pos", 0)))
            written = bool(state.get("full", False)) or pos > 0
            for key in ("dones", "terminated", "truncated"):
                if key in buf:
                    arr = np.asarray(buf[key])
                    if arr.size and written:
                        arr = arr.copy()
                        arr[(pos - 1) % arr.shape[0]] = 1
                        buf[key] = arr
        return state

    @staticmethod
    def _buffer_state(rb) -> Dict[str, Any]:
        """Snapshot buffer state with trailing terminal flags forced."""
        if isinstance(rb, (list, tuple)):  # per-env buffer lists (AsyncReplayBuffer parts)
            return {"__list__": [CheckpointCallback._buffer_state(b) for b in rb]}
        state = rb.state_dict()
        if isinstance(state.get("buffers"), list):  # EnvIndependentReplayBuffer
            state["buffers"] = [
                CheckpointCallback._force_terminal(s) for s in state["buffers"]
            ]
            return state
        return CheckpointCallback._force_terminal(state)

    # -- hooks (dispatched by fabric.call) -------------------------------

    def on_checkpoint_coupled(
        self,
        fabric,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer=None,
        sharding_meta: Optional[Dict[str, Any]] = None,
        **_: Any,
    ) -> None:
        from sheeprl_tpu.ckpt import get_checkpoint_manager

        rb_state = self._buffer_state(replay_buffer) if replay_buffer is not None else None
        get_checkpoint_manager().save(
            ckpt_path,
            state,
            rb_state=rb_state,
            fabric=fabric,
            keep_last=self.keep_last,
            sharding_meta=sharding_meta,
        )

    def on_checkpoint_player(self, fabric, ckpt_path: str, state: Dict[str, Any], replay_buffer=None, **_: Any):
        self.on_checkpoint_coupled(fabric, ckpt_path, state, replay_buffer)

    def on_checkpoint_trainer(self, fabric, ckpt_path: str, state: Dict[str, Any], **_: Any):
        self.on_checkpoint_coupled(fabric, ckpt_path, state)
