"""Checkpoint callback.

Reference behavior (``sheeprl/utils/callback.py:10-92``): dispatched via
``fabric.call("on_checkpoint_{coupled|player|trainer}")``; optionally embeds
the replay-buffer state with the last stored ``dones`` forced to 1 so the
in-progress episode terminates cleanly on restore (callback.py:32-40,59-64),
and prunes old checkpoints. Buffers are host-side numpy, so each process saves
its own buffer state alongside the (replicated) model pytree.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Any, Dict, Optional

import numpy as np


class CheckpointCallback:
    """Saves `state` (a pytree of arrays + counters) and optionally buffers."""

    def __init__(self, keep_last: Optional[int] = None):
        self.keep_last = keep_last

    # -- buffer embedding ------------------------------------------------

    @staticmethod
    def _buffer_state(rb) -> Dict[str, Any]:
        """Snapshot buffer state with trailing dones forced terminal."""
        if isinstance(rb, (list, tuple)):  # per-env buffer lists (AsyncReplayBuffer parts)
            return {"__list__": [CheckpointCallback._buffer_state(b) for b in rb]}
        state = rb.state_dict()
        buf = state.get("buffer")
        if isinstance(buf, dict):
            # force the step before `pos` to be terminal (reference :32-40)
            for key in ("dones", "terminated", "truncated"):
                if key in buf and key == "dones":
                    arr = np.asarray(buf[key])
                    pos = state.get("pos", 0)
                    if arr.size and len(rb) > 0:
                        arr = arr.copy()
                        arr[(pos - 1) % arr.shape[0]] = 1
                        buf[key] = arr
        return state

    def _prune(self, ckpt_dir: str) -> None:
        if not self.keep_last or not os.path.isdir(ckpt_dir):
            return
        paths = glob.glob(os.path.join(ckpt_dir, "ckpt_*"))

        def step_of(p: str) -> int:
            m = re.search(r"ckpt_(\d+)", os.path.basename(p))
            return int(m.group(1)) if m else -1

        for path in sorted(paths, key=step_of)[: -self.keep_last]:
            try:
                import shutil

                shutil.rmtree(path, ignore_errors=True)
            except OSError:
                pass

    # -- hooks (dispatched by fabric.call) -------------------------------

    def on_checkpoint_coupled(
        self,
        fabric,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer=None,
        **_: Any,
    ) -> None:
        if replay_buffer is not None:
            state = {**state, "rb": self._buffer_state(replay_buffer)}
        fabric.save(ckpt_path, state)
        self._prune(os.path.dirname(ckpt_path))

    def on_checkpoint_player(self, fabric, ckpt_path: str, state: Dict[str, Any], replay_buffer=None, **_: Any):
        self.on_checkpoint_coupled(fabric, ckpt_path, state, replay_buffer)

    def on_checkpoint_trainer(self, fabric, ckpt_path: str, state: Dict[str, Any], **_: Any):
        self.on_checkpoint_coupled(fabric, ckpt_path, state)
