"""Environment factory.

Re-implementation of the reference ``make_env`` pipeline
(``sheeprl/utils/env.py:25-203``) on gymnasium 1.x: every env is normalized to
a ``gym.spaces.Dict`` observation space whose image keys are uint8 CHW frames
resized to ``env.screen_size`` (optionally grayscaled / frame-stacked), and
whose vector keys pass through untouched. Envs run on the CPU host; the data
layer stages their numpy output to the TPU.

Pipeline order (matching the reference): wrapper target → ActionRepeat →
MaskVelocity → dict-ification → resize/grayscale/CHW → FrameStack →
RewardAsObservation → seeding → TimeLimit → RecordEpisodeStatistics →
RecordVideo.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, Optional

import cv2
import gymnasium as gym
import numpy as np

from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    FrameStack,
    GrayscaleRenderWrapper,
    MaskVelocityWrapper,
    RewardAsObservationWrapper,
)


def _dictify_observations(env: gym.Env, cfg) -> gym.Env:
    """Wrap non-dict observation spaces into a single-key Dict space.

    Mirrors reference env.py:88-130: 1-D Box → the first mlp key (default
    ``state``); 2/3-D Box → the first cnn key (default ``rgb``).
    """
    space = env.observation_space
    if isinstance(space, gym.spaces.Dict):
        return env
    if not isinstance(space, gym.spaces.Box):
        raise ValueError(f"Unsupported observation space: {type(space)}")
    if len(space.shape) < 2:
        keys = cfg.mlp_keys.encoder
        if keys:
            if len(keys) > 1:
                warnings.warn(
                    f"Multiple mlp keys specified but {cfg.env.id} has a single vector "
                    f"observation; keeping only {keys[0]}"
                )
            key = keys[0]
        else:
            key = "state"
            cfg.mlp_keys.encoder = [key]
    elif len(space.shape) <= 3:
        keys = cfg.cnn_keys.encoder
        if keys:
            if len(keys) > 1:
                warnings.warn(
                    f"Multiple cnn keys specified but {cfg.env.id} has a single pixel "
                    f"observation; keeping only {keys[0]}"
                )
            key = keys[0]
        else:
            key = "rgb"
            cfg.cnn_keys.encoder = [key]
    else:
        raise ValueError(f"Unsupported Box observation rank: {space.shape}")
    return gym.wrappers.TransformObservation(
        env, lambda obs, k=key: {k: obs}, observation_space=gym.spaces.Dict({key: space})
    )


def _image_transform(cfg, cnn_keys) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Per-step image normalization: resize → grayscale → uint8 CHW
    (reference env.py:136-171)."""
    screen = cfg.env.screen_size
    grayscale = cfg.env.grayscale

    def transform(obs: Dict[str, Any]) -> Dict[str, Any]:
        for k in cnn_keys:
            frame = obs[k]
            is_3d = frame.ndim == 3
            is_gray = not is_3d or frame.shape[0] == 1 or frame.shape[-1] == 1
            channel_first = not is_3d or frame.shape[0] in (1, 3)
            if not is_3d:
                frame = frame[None]
            if channel_first:
                frame = np.transpose(frame, (1, 2, 0))
            if frame.shape[:-1] != (screen, screen):
                frame = cv2.resize(frame, (screen, screen), interpolation=cv2.INTER_AREA)
            if grayscale and not is_gray:
                frame = cv2.cvtColor(frame, cv2.COLOR_RGB2GRAY)
            if frame.ndim == 2:
                frame = frame[..., None]
                if not grayscale:
                    frame = np.repeat(frame, 3, axis=-1)
            obs[k] = np.transpose(frame, (2, 0, 1))
        return obs

    return transform


def make_env(
    cfg,
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    """Return a thunk that builds one fully-wrapped env (reference env.py:25-203)."""

    def thunk() -> gym.Env:
        try:
            env_spec = gym.spec(cfg.env.id).entry_point
        except Exception:
            env_spec = ""

        kwargs = {}
        if "seed" in cfg.env.wrapper:
            kwargs["seed"] = seed
        if "rank" in cfg.env.wrapper:
            kwargs["rank"] = rank + vector_env_idx
        env = instantiate(cfg.env.wrapper, **kwargs)

        # Atari-style envs repeat actions internally (reference env.py:75-80)
        if cfg.env.action_repeat > 1 and "atari" not in str(env_spec):
            env = ActionRepeat(env, cfg.env.action_repeat)
        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        env = _dictify_observations(env, cfg)

        env_cnn_keys = {k for k, v in env.observation_space.spaces.items() if len(v.shape) in (2, 3)}
        user_cnn_keys = set(cfg.cnn_keys.encoder or [])
        cnn_keys = sorted(env_cnn_keys & user_cnn_keys)

        if cnn_keys:
            channels = 1 if cfg.env.grayscale else 3
            new_space = dict(env.observation_space.spaces)
            for k in cnn_keys:
                new_space[k] = gym.spaces.Box(
                    0, 255, (channels, cfg.env.screen_size, cfg.env.screen_size), np.uint8
                )
            env = gym.wrappers.TransformObservation(
                env, _image_transform(cfg, cnn_keys), observation_space=gym.spaces.Dict(new_space)
            )

            if cfg.env.frame_stack > 1:
                if cfg.env.frame_stack_dilation <= 0:
                    raise ValueError(
                        "The frame stack dilation argument must be greater than zero, "
                        f"got: {cfg.env.frame_stack_dilation}"
                    )
                env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        if cfg.env.get("reward_as_observation", False):
            env = RewardAsObservationWrapper(env)

        env.action_space.seed(seed)
        env.observation_space.seed(seed)
        if cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, max_episode_steps=cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if cfg.env.capture_video and rank == 0 and vector_env_idx == 0 and run_name is not None:
            import importlib.util

            if importlib.util.find_spec("moviepy") is None:
                warnings.warn(
                    "env.capture_video=True but moviepy is not installed; "
                    "skipping video capture (pip install moviepy)"
                )
            else:
                if cfg.env.grayscale:
                    env = GrayscaleRenderWrapper(env)
                video_dir = os.path.join(run_name, prefix + "_videos" if prefix else "videos")
                env = gym.wrappers.RecordVideo(env, video_dir, disable_logger=True)
        return env

    return thunk


def vectorize_envs(thunks, cfg):
    """Legacy shim: wrap prebuilt thunks in the configured vector backend.

    The backend decision (``env.vectorization`` / legacy ``env.sync_env``)
    and every backend implementation live in ``sheeprl_tpu/envs/vector``
    now; algorithm entrypoints must use ``make_vector_env`` (enforced by
    ``tools/lint_vecenv.py``) — this wrapper remains for diagnostics/tools
    that build custom thunks.
    """
    from sheeprl_tpu.envs.vector.factory import vectorize_thunks

    return vectorize_thunks(thunks, cfg)


def get_dummy_env(id: str) -> gym.Env:  # noqa: A002 — kwarg name fixed by env/dummy.yaml
    """Deterministic dummy envs used by the test suite (reference env.py:206-221)."""
    env_id = id
    if "continuous" in env_id:
        from sheeprl_tpu.envs.dummy import ContinuousDummyEnv

        return ContinuousDummyEnv()
    if "multidiscrete" in env_id:
        from sheeprl_tpu.envs.dummy import MultiDiscreteDummyEnv

        return MultiDiscreteDummyEnv()
    if "discrete" in env_id:
        from sheeprl_tpu.envs.dummy import DiscreteDummyEnv

        return DiscreteDummyEnv()
    raise ValueError(f"Unrecognized dummy environment: {env_id}")
