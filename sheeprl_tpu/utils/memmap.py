"""Disk-backed numpy arrays.

TPU-native counterpart of the reference's ``sheeprl/utils/memmap.MemmapArray``
(the v0.5.x numpy design its tests target — tests/test_utils/test_memmap.py):
a picklable, ownership-tracking wrapper over ``np.memmap``. On TPU hosts this
is the cold tier of the replay buffer: observations live on disk / host RAM
and only sampled batches are staged to HBM by the prefetcher.
"""

from __future__ import annotations

import os
import tempfile
import uuid
from typing import Optional, Sequence, Union

import numpy as np

ACCEPTED_MEMMAP_MODES = ("r+", "w+")


def validate_memmap_mode(mode: str) -> str:
    if mode not in ACCEPTED_MEMMAP_MODES:
        raise ValueError(
            f"Accepted values for memmap_mode are {ACCEPTED_MEMMAP_MODES}, got '{mode}'"
        )
    return mode


class MemmapArray:
    """A numpy array backed by a file on disk.

    The instance that created the file owns it and unlinks it on deletion;
    pickled/unpickled copies share the file without ownership (reference
    semantics, test_memmap.py:46-57).
    """

    def __init__(
        self,
        shape: Sequence[int],
        dtype: Union[np.dtype, type] = np.float32,
        filename: Optional[str] = None,
        mode: str = "r+",
    ):
        validate_memmap_mode(mode)
        self._shape = tuple(int(s) for s in shape)
        self._dtype = np.dtype(dtype)
        if filename is None:
            filename = os.path.join(tempfile.gettempdir(), f"memmap_{uuid.uuid4().hex}.memmap")
        self._filename = os.path.abspath(filename)
        self._mode = mode
        self._has_ownership = True
        self._array: Optional[np.memmap] = None
        os.makedirs(os.path.dirname(self._filename), exist_ok=True)
        existed = os.path.isfile(self._filename)
        self._array = np.memmap(
            self._filename,
            dtype=self._dtype,
            mode="r+" if existed and mode == "r+" else "w+",
            shape=self._shape,
        )

    # -- construction -----------------------------------------------------

    @classmethod
    def from_array(
        cls,
        array: Union[np.ndarray, "MemmapArray"],
        filename: Optional[str] = None,
        mode: str = "r+",
    ) -> "MemmapArray":
        if isinstance(array, MemmapArray):
            array = array.array
        array = np.asarray(array)
        out = cls(shape=array.shape, dtype=array.dtype, filename=filename, mode=mode)
        out._array[...] = array
        out._array.flush()
        return out

    # -- core accessors ---------------------------------------------------

    @property
    def array(self) -> np.memmap:
        if self._array is None:
            raise RuntimeError("The MemmapArray has been closed; the file no longer exists")
        return self._array

    @array.setter
    def array(self, value: np.ndarray) -> None:
        arr = self.array
        if tuple(value.shape) != self._shape:
            raise ValueError(f"Shape mismatch: expected {self._shape}, got {value.shape}")
        arr[...] = value
        arr.flush()

    @property
    def filename(self) -> str:
        return self._filename

    @property
    def shape(self) -> tuple:
        return self._shape

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    # -- numpy protocol ---------------------------------------------------

    def __array__(self, dtype=None, copy=None) -> np.ndarray:
        arr = self.array
        if dtype is not None:
            arr = arr.astype(dtype, copy=False)
        if copy:
            arr = np.array(arr)
        return np.asarray(arr)

    def __getitem__(self, item):
        return self.array[item]

    def __setitem__(self, item, value):
        self.array[item] = value

    def __eq__(self, other):
        return self.array == (other.array if isinstance(other, MemmapArray) else other)

    def __len__(self):
        return self._shape[0]

    def __repr__(self):
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, file={self._filename})"

    # -- lifecycle --------------------------------------------------------

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_array"] = None
        state["_has_ownership"] = False
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        if os.path.isfile(self._filename):
            self._array = np.memmap(self._filename, dtype=self._dtype, mode="r+", shape=self._shape)

    def __del__(self):
        try:
            if getattr(self, "_array", None) is not None:
                del self._array
            self._array = None
            if getattr(self, "_has_ownership", False) and os.path.isfile(self._filename):
                os.unlink(self._filename)
                self._has_ownership = False
        except Exception:
            pass
