"""Optimizers: torch-constructor surface over optax.

The reference instantiates ``torch.optim.{Adam,AdamW,SGD}`` straight from
config (``ppo.py:192``, ``configs/optim/*.yaml``); the alias table in
:mod:`sheeprl_tpu.config.instantiate` routes those targets here. Each factory
returns an ``optax.GradientTransformation`` wrapped in
``optax.inject_hyperparams`` so the learning rate lives *in the optimizer
state pytree* — schedules (PPO's ``anneal_lr``) become functional state
updates inside the jitted step instead of host-side mutation.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import optax

#: clip-threshold side table keyed by id() of the returned transformation.
#: ``GradientTransformation`` is a NamedTuple (no attributes, no weakrefs),
#: so the factory records the threshold here and keeps a strong reference to
#: the tx itself — the identity check in :func:`clip_norm_of` makes a
#: recycled id() harmless. This is how the learn probes SURFACE the clip
#: threshold (``learn/clip_frac``) instead of recomputing it from config.
_CLIP_NORMS: Dict[int, Tuple[optax.GradientTransformation, float]] = {}


def _clipped(tx: optax.GradientTransformation, max_grad_norm: Optional[float]) -> optax.GradientTransformation:
    if max_grad_norm and max_grad_norm > 0:
        out = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
        _CLIP_NORMS[id(out)] = (out, float(max_grad_norm))
        return out
    return tx


def clip_norm_of(tx) -> Optional[float]:
    """The ``clip_by_global_norm`` threshold this factory wrapped ``tx``
    with, or None when the optimizer is unclipped (or not from here)."""
    entry = _CLIP_NORMS.get(id(tx))
    if entry is not None and entry[0] is tx:
        return entry[1]
    return None


def Adam(
    lr: float = 1e-3,
    betas: Sequence[float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    max_grad_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    b1, b2 = betas
    if weight_decay:
        base = optax.inject_hyperparams(optax.adamw)(
            learning_rate=lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay
        )
    else:
        base = optax.inject_hyperparams(optax.adam)(learning_rate=lr, b1=b1, b2=b2, eps=eps)
    return _clipped(base, max_grad_norm)


def AdamW(
    lr: float = 1e-3,
    betas: Sequence[float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 1e-2,
    max_grad_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    b1, b2 = betas
    base = optax.inject_hyperparams(optax.adamw)(
        learning_rate=lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay
    )
    return _clipped(base, max_grad_norm)


def SGD(
    lr: float = 1e-2,
    momentum: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    max_grad_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    base = optax.inject_hyperparams(optax.sgd)(
        learning_rate=lr, momentum=momentum if momentum else None, nesterov=nesterov
    )
    if weight_decay:
        base = optax.chain(optax.add_decayed_weights(weight_decay), base)
    return _clipped(base, max_grad_norm)


def get_lr(opt_state) -> float:
    """Read the current injected learning rate out of an optimizer state."""
    state = opt_state
    if isinstance(state, tuple) and hasattr(state, "_fields") is False:
        # chained: inject_hyperparams state is the last element
        for part in state:
            if hasattr(part, "hyperparams"):
                state = part
                break
    if hasattr(state, "hyperparams"):
        return float(state.hyperparams["learning_rate"])
    raise ValueError("Optimizer state carries no injected learning rate")


def set_lr(opt_state, lr):
    """Functionally set the injected learning rate (returns a new state)."""
    if hasattr(opt_state, "hyperparams"):
        hp = dict(opt_state.hyperparams)
        hp["learning_rate"] = lr
        return opt_state._replace(hyperparams=hp)
    if isinstance(opt_state, tuple):
        # chained transforms: a plain tuple of per-transform states
        return tuple(set_lr(p, lr) if hasattr(p, "hyperparams") else p for p in opt_state)
    raise ValueError("Optimizer state carries no injected learning rate")
