"""General utilities.

TPU-native re-implementation of the helpers in the reference's
``sheeprl/utils/utils.py`` (dotdict :15, gae :38-74, normalize_tensor :95,
polynomial_decay :107, symlog/symexp :122-127, print_config :130-159) — same
behavior, jnp/lax instead of torch, GAE as a ``lax.scan`` instead of a Python
reverse loop.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class dotdict(dict):
    """A dict with attribute-style access, recursively applied.

    Mirrors the reference `dotdict` (sheeprl/utils/utils.py:15-35): nested
    dictionaries are converted on construction and on item assignment.
    """

    def __init__(self, *args, **kwargs):
        super().__init__()
        src = dict(*args, **kwargs)
        for k, v in src.items():
            self[k] = v

    @classmethod
    def _wrap(cls, value):
        if isinstance(value, dotdict):
            return value
        if isinstance(value, dict):
            return cls(value)
        if isinstance(value, (list, tuple)):
            return type(value)(cls._wrap(v) for v in value)
        return value

    def __setitem__(self, key, value):
        super().__setitem__(key, self._wrap(value))

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name, value):
        self[name] = value

    def __delattr__(self, name):
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def as_dict(self) -> Dict[str, Any]:
        """Convert back to plain nested dicts (for yaml dumps / orbax)."""
        out = {}
        for k, v in self.items():
            if isinstance(v, dotdict):
                out[k] = v.as_dict()
            elif isinstance(v, (list, tuple)):
                out[k] = type(v)(x.as_dict() if isinstance(x, dotdict) else x for x in v)
            else:
                out[k] = v
        return out


def symlog(x: jnp.ndarray) -> jnp.ndarray:
    """Symmetric log transform (reference utils.py:122-123)."""
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`symlog` (reference utils.py:126-127)."""
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def gae(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    dones: jnp.ndarray,
    next_value: jnp.ndarray,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized advantage estimation over a rollout of shape ``[T, ...]``.

    Matches the reference semantics exactly (utils.py:38-74): ``dones[t]`` is
    the done flag of *transition t* (episode ended at step t), so the bootstrap
    from ``t+1`` is masked by ``1 - dones[t]``. Implemented as a single
    reversed ``lax.scan`` so XLA compiles one fused loop instead of T Python
    iterations.

    Returns ``(returns, advantages)``, both ``[T, ...]``.
    """
    rewards = jnp.asarray(rewards)
    values = jnp.asarray(values)
    dones = jnp.asarray(dones, dtype=rewards.dtype)
    next_value = jnp.asarray(next_value, dtype=rewards.dtype)

    # value of the next observation for every t.
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)

    def step(carry, inp):
        lastgaelam = carry
        reward, value, nvalue, done = inp
        nonterminal = 1.0 - done
        delta = reward + gamma * nvalue * nonterminal - value
        lastgaelam = delta + gamma * gae_lambda * nonterminal * lastgaelam
        return lastgaelam, lastgaelam

    _, advantages = jax.lax.scan(
        step,
        jnp.zeros_like(next_value),
        (rewards, values, next_values, dones),
        reverse=True,
    )
    returns = advantages + values
    return returns, advantages


def normalize_tensor(x: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    """Standardize to zero mean / unit variance (reference utils.py:95-104)."""
    return (x - x.mean()) / (x.std() + eps)


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Polynomial annealing schedule (reference utils.py:107-119)."""
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


def print_config(cfg, logger=print) -> None:
    """Print the run config as a tree (reference utils.py:130-159 uses rich)."""
    try:
        import rich.tree
        import rich.syntax
        import rich

        tree = rich.tree.Tree("CONFIG")
        import yaml

        data = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
        for key, value in data.items():
            branch = tree.add(str(key))
            if isinstance(value, dict):
                branch.add(rich.syntax.Syntax(yaml.dump(value, sort_keys=False), "yaml"))
            else:
                branch.add(str(value))
        rich.print(tree)
    except Exception:
        import pprint

        logger(pprint.pformat(cfg))


def save_configs(cfg, log_dir: str) -> None:
    """Persist the composed config under ``<log_dir>/.hydra/config.yaml``.

    Checkpoint-resume and evaluation re-read this file (reference
    cli.py:26,280); we keep the same path layout.
    """
    import yaml

    os.makedirs(os.path.join(log_dir, ".hydra"), exist_ok=True)
    data = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
    with open(os.path.join(log_dir, ".hydra", "config.yaml"), "w") as f:
        yaml.safe_dump(data, f, sort_keys=False)


def fetch_losses_if_observed(losses, aggregator=None):
    """Materialize a device loss vector only when something will read it —
    the metric aggregator — or when the global timer is live (the blocking
    fetch keeps Time/train_time honest). With both disabled the fetch is a
    pure device->host round trip per update (expensive on remote-attached
    accelerators), so the array is returned un-materialized."""
    from sheeprl_tpu.utils.timer import timer

    if not timer.disabled or (aggregator is not None and not aggregator.disabled):
        return np.asarray(losses)
    return losses


def enable_persistent_compilation_cache(path: str = None) -> None:
    """Point jax's persistent XLA compilation cache at a durable directory so
    repeated runs skip recompiles (~7 s of a short PPO benchmark; the
    reference's torch has no compile step to amortize). Override the
    location with ``SHEEPRL_JAX_CACHE``; set it to ``0`` to disable."""
    loc = os.environ.get("SHEEPRL_JAX_CACHE", path) or os.path.join(
        os.path.expanduser("~"), ".cache", "sheeprl_tpu", "xla_cache"
    )
    if loc == "0":
        return
    try:
        os.makedirs(loc, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", loc)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception as exc:  # pragma: no cover - cache is best-effort
        warnings.warn(f"persistent compilation cache disabled: {exc}")


def unwrap_fabric(module):  # pragma: no cover - parity shim
    """Parity shim with the reference API: params are already plain pytrees."""
    return module


def conform_pytree(template: Any, restored: Any) -> Any:
    """Rebuild ``restored`` (raw containers from an orbax template-less
    restore: dicts and lists) in the *structure* of ``template`` — NamedTuples
    (optax states) are reconstructed from lists or field dicts, tuples from
    lists, and dict keys present on disk but absent from the template are
    dropped. Leaves come from ``restored``.
    """
    if isinstance(template, dict):
        return type(template)(
            {k: conform_pytree(template[k], restored[k]) for k in template}
        )
    if isinstance(template, tuple) and hasattr(template, "_fields"):  # NamedTuple
        if len(template) == 0 or restored is None:  # e.g. optax EmptyState
            return template
        vals = restored
        if isinstance(restored, dict):
            vals = [restored[f] for f in template._fields]
        return type(template)(*(conform_pytree(t, r) for t, r in zip(template, vals)))
    if isinstance(template, (list, tuple)):
        if restored is None:
            return template
        if len(template) != len(restored):
            raise ValueError(
                f"conform_pytree: structure length mismatch — template has "
                f"{len(template)} entries, restored has {len(restored)} "
                "(checkpoint saved with a different optimizer/transform chain?)"
            )
        return type(template)(conform_pytree(t, r) for t, r in zip(template, restored))
    return restored
