"""General utilities.

TPU-native re-implementation of the helpers in the reference's
``sheeprl/utils/utils.py`` (dotdict :15, gae :38-74, normalize_tensor :95,
polynomial_decay :107, symlog/symexp :122-127, print_config :130-159) — same
behavior, jnp/lax instead of torch, GAE as a ``lax.scan`` instead of a Python
reverse loop.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class dotdict(dict):
    """A dict with attribute-style access, recursively applied.

    Mirrors the reference `dotdict` (sheeprl/utils/utils.py:15-35): nested
    dictionaries are converted on construction and on item assignment.
    """

    def __init__(self, *args, **kwargs):
        super().__init__()
        src = dict(*args, **kwargs)
        for k, v in src.items():
            self[k] = v

    @classmethod
    def _wrap(cls, value):
        if isinstance(value, dotdict):
            return value
        if isinstance(value, dict):
            return cls(value)
        if isinstance(value, (list, tuple)):
            return type(value)(cls._wrap(v) for v in value)
        return value

    def __setitem__(self, key, value):
        super().__setitem__(key, self._wrap(value))

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name, value):
        self[name] = value

    def __delattr__(self, name):
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def as_dict(self) -> Dict[str, Any]:
        """Convert back to plain nested dicts (for yaml dumps / orbax)."""
        out = {}
        for k, v in self.items():
            if isinstance(v, dotdict):
                out[k] = v.as_dict()
            elif isinstance(v, (list, tuple)):
                out[k] = type(v)(x.as_dict() if isinstance(x, dotdict) else x for x in v)
            else:
                out[k] = v
        return out


def symlog(x: jnp.ndarray) -> jnp.ndarray:
    """Symmetric log transform (reference utils.py:122-123)."""
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`symlog` (reference utils.py:126-127)."""
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def gae(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    dones: jnp.ndarray,
    next_value: jnp.ndarray,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized advantage estimation over a rollout of shape ``[T, ...]``.

    Matches the reference semantics exactly (utils.py:38-74): ``dones[t]`` is
    the done flag of *transition t* (episode ended at step t), so the bootstrap
    from ``t+1`` is masked by ``1 - dones[t]``. Implemented as a single
    reversed ``lax.scan`` so XLA compiles one fused loop instead of T Python
    iterations.

    Returns ``(returns, advantages)``, both ``[T, ...]``.
    """
    rewards = jnp.asarray(rewards)
    values = jnp.asarray(values)
    dones = jnp.asarray(dones, dtype=rewards.dtype)
    next_value = jnp.asarray(next_value, dtype=rewards.dtype)

    # value of the next observation for every t.
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)

    def step(carry, inp):
        lastgaelam = carry
        reward, value, nvalue, done = inp
        nonterminal = 1.0 - done
        delta = reward + gamma * nvalue * nonterminal - value
        lastgaelam = delta + gamma * gae_lambda * nonterminal * lastgaelam
        return lastgaelam, lastgaelam

    _, advantages = jax.lax.scan(
        step,
        jnp.zeros_like(next_value),
        (rewards, values, next_values, dones),
        reverse=True,
    )
    returns = advantages + values
    return returns, advantages


def normalize_tensor(x: jnp.ndarray, eps: float = 1e-8) -> jnp.ndarray:
    """Standardize to zero mean / unit variance (reference utils.py:95-104)."""
    return (x - x.mean()) / (x.std() + eps)


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Polynomial annealing schedule (reference utils.py:107-119)."""
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


def print_config(cfg, logger=print) -> None:
    """Print the run config as a tree (reference utils.py:130-159 uses rich)."""
    try:
        import rich.tree
        import rich.syntax
        import rich

        tree = rich.tree.Tree("CONFIG")
        import yaml

        data = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
        for key, value in data.items():
            branch = tree.add(str(key))
            if isinstance(value, dict):
                branch.add(rich.syntax.Syntax(yaml.dump(value, sort_keys=False), "yaml"))
            else:
                branch.add(str(value))
        rich.print(tree)
    except Exception:
        import pprint

        logger(pprint.pformat(cfg))


def save_configs(cfg, log_dir: str) -> None:
    """Persist the composed config under ``<log_dir>/.hydra/config.yaml``.

    Checkpoint-resume and evaluation re-read this file (reference
    cli.py:26,280); we keep the same path layout.
    """
    import yaml

    os.makedirs(os.path.join(log_dir, ".hydra"), exist_ok=True)
    data = cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg)
    with open(os.path.join(log_dir, ".hydra", "config.yaml"), "w") as f:
        yaml.safe_dump(data, f, sort_keys=False)


def fetch_losses_if_observed(losses, aggregator=None):
    """Materialize a device loss vector only when something will read it —
    the metric aggregator — or when the global timer is live (the blocking
    fetch keeps Time/train_time honest). With both disabled the fetch is a
    pure device->host round trip per update (expensive on remote-attached
    accelerators), so the array is returned un-materialized."""
    from sheeprl_tpu.utils.timer import timer

    if not timer.disabled or (aggregator is not None and not aggregator.disabled):
        return np.asarray(losses)
    return losses


def params_on_device(tree):
    """Materialize a checkpoint param tree as numpy and park it on the
    default accelerator ONCE. Evaluation players are jitted fns called once
    per env step; numpy leaves would re-upload the whole tree on every call
    (seconds per step through a tunneled host link)."""
    import jax

    return jax.device_put(
        jax.tree_util.tree_map(np.asarray, tree), jax.devices()[0]
    )


def enable_persistent_compilation_cache(path: str = None) -> None:
    """Point jax's persistent XLA compilation cache at a durable directory so
    repeated runs skip recompiles (~7 s of a short PPO benchmark; the
    reference's torch has no compile step to amortize). Override the
    location with ``SHEEPRL_JAX_CACHE``; set it to ``0`` to disable."""
    loc = os.environ.get("SHEEPRL_JAX_CACHE", path)
    if loc == "0":
        return
    if not loc:
        # Partition the default cache by host-CPU fingerprint: XLA:CPU AOT
        # entries bake in the compile machine's ISA features, and loading
        # them on a different host (containers migrate between rounds)
        # warns about potential SIGILL. A TPU entry keyed the same way just
        # recompiles once per host.
        import hashlib
        import platform

        try:
            with open("/proc/cpuinfo") as f:
                flags = next((l for l in f if l.startswith("flags")), platform.machine())
        except OSError:
            flags = platform.machine()
        fp = hashlib.sha1(flags.encode()).hexdigest()[:10]
        loc = os.path.join(
            os.path.expanduser("~"), ".cache", "sheeprl_tpu", f"xla_cache_{fp}"
        )
    try:
        os.makedirs(loc, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", loc)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception as exc:  # pragma: no cover - cache is best-effort
        warnings.warn(f"persistent compilation cache disabled: {exc}")


def unwrap_fabric(module):  # pragma: no cover - parity shim
    """Parity shim with the reference API: params are already plain pytrees."""
    return module


def conform_pytree(template: Any, restored: Any) -> Any:
    """Rebuild ``restored`` (raw containers from an orbax template-less
    restore: dicts and lists) in the *structure* of ``template`` — NamedTuples
    (optax states) are reconstructed from lists or field dicts, tuples from
    lists, and dict keys present on disk but absent from the template are
    dropped. Leaves come from ``restored``.
    """
    if isinstance(template, dict):
        return type(template)(
            {k: conform_pytree(template[k], restored[k]) for k in template}
        )
    if isinstance(template, tuple) and hasattr(template, "_fields"):  # NamedTuple
        if len(template) == 0 or restored is None:  # e.g. optax EmptyState
            return template
        vals = restored
        if isinstance(restored, dict):
            vals = [restored[f] for f in template._fields]
        return type(template)(*(conform_pytree(t, r) for t, r in zip(template, vals)))
    if isinstance(template, (list, tuple)):
        if restored is None:
            return template
        if len(template) != len(restored):
            raise ValueError(
                f"conform_pytree: structure length mismatch — template has "
                f"{len(template)} entries, restored has {len(restored)} "
                "(checkpoint saved with a different optimizer/transform chain?)"
            )
        return type(template)(conform_pytree(t, r) for t, r in zip(template, restored))
    return restored


def _rename_trunk_params(value: dict) -> None:
    mlp = value.pop("MLP_0")
    unexpected = set(mlp) - {"Dense_0", "LayerNorm_0"}
    if unexpected:
        # fail loudly instead of silently dropping parameters if the stored
        # trunk layout ever grows entries this migration doesn't carry over
        raise ValueError(
            "migrate_legacy_checkpoint: representation-model MLP_0 contains "
            f"unexpected entries {sorted(unexpected)}; refusing to migrate a "
            "layout this shim does not understand"
        )
    dense = mlp.get("Dense_0", {})
    if "kernel" in dense:
        value["trunk_kernel"] = dense["kernel"]
    if "bias" in dense:
        value["trunk_bias"] = dense["bias"]
    if "LayerNorm_0" in mlp:
        value["trunk_ln"] = mlp["LayerNorm_0"]


def migrate_legacy_checkpoint(template: Any, restored: Any) -> Any:
    """Rename pre-split posterior-trunk parameters in-place and return the tree.

    The DV3-family ``_RepresentationModel`` used to be a plain
    ``_StochasticModel`` (MLP + head); splitting the embed projection out of
    the RSSM scan renamed its parameters without changing the math — the
    joint first-layer kernel is still stored as one ``[h+embed, hidden]``
    matrix:

    - ``representation_model/MLP_0/Dense_0/kernel`` -> ``trunk_kernel``
    - ``representation_model/MLP_0/Dense_0/bias``   -> ``trunk_bias``
    - ``representation_model/MLP_0/LayerNorm_0``    -> ``trunk_ln``

    Checkpoints written before the rename load transparently through this
    shim (applied by ``Fabric.load`` before structure conforming).

    The walk is guided by ``template`` (the caller's live state pytree): a
    subtree is renamed only where the template *expects* the split layout
    (has ``trunk_kernel``) — DV1/DV2 still use the joint ``MLP_0`` layout
    under the same ``representation_model`` key and must pass through
    untouched.  Traversal mirrors ``conform_pytree``'s container handling so
    optimizer moments (optax NamedTuple chains restored as lists, whose
    mu/nu trees mirror the param structure) migrate too.
    """
    if isinstance(template, dict) and isinstance(restored, dict):
        for key, t_val in template.items():
            if key not in restored:
                continue
            r_val = restored[key]
            if (
                key == "representation_model"
                and isinstance(t_val, dict)
                and "trunk_kernel" in t_val
                and isinstance(r_val, dict)
                and "MLP_0" in r_val
                and "trunk_kernel" not in r_val
            ):
                _rename_trunk_params(r_val)
            migrate_legacy_checkpoint(t_val, r_val)
        return restored
    if isinstance(template, tuple) and hasattr(template, "_fields"):  # NamedTuple
        vals = restored
        if isinstance(restored, dict):
            vals = [restored.get(f) for f in template._fields]
        if isinstance(vals, (list, tuple)):
            for t_val, r_val in zip(template, vals):
                migrate_legacy_checkpoint(t_val, r_val)
        return restored
    if isinstance(template, (list, tuple)) and isinstance(restored, (list, tuple)):
        for t_val, r_val in zip(template, restored):
            migrate_legacy_checkpoint(t_val, r_val)
        return restored
    return restored


def migrate_dv3_checkpoint(restored: Any) -> Any:
    """Template-free variant of ``migrate_legacy_checkpoint`` for consumers
    that load a checkpoint *known* to be DV3-family without a live state tree
    (evaluation and P2E-DV3 finetuning load stateless, then build the agent
    from the stored config): every ``representation_model/MLP_0`` subtree in
    a DV3-family checkpoint is pre-rename by definition, so rename them all.
    Do NOT use on DV1/DV2 checkpoints — their current layout looks identical.
    """
    if isinstance(restored, dict):
        for key, value in restored.items():
            if (
                key == "representation_model"
                and isinstance(value, dict)
                and "MLP_0" in value
                and "trunk_kernel" not in value
            ):
                _rename_trunk_params(value)
            migrate_dv3_checkpoint(value)
    elif isinstance(restored, (list, tuple)):
        for value in restored:
            migrate_dv3_checkpoint(value)
    return restored
