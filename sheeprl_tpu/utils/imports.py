"""Optional-dependency probes (reference sheeprl/utils/imports.py:1-15)."""

from __future__ import annotations

import importlib.util
import platform


def _module_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except Exception:
        return False


_IS_WINDOWS = platform.system() == "Windows"

_IS_ATARI_AVAILABLE = _module_available("ale_py")
_IS_ATARI_ROMS_AVAILABLE = _IS_ATARI_AVAILABLE
_IS_DMC_AVAILABLE = _module_available("dm_control")
_IS_CRAFTER_AVAILABLE = _module_available("crafter")
_IS_DIAMBRA_AVAILABLE = _module_available("diambra")
_IS_DIAMBRA_ARENA_AVAILABLE = _module_available("diambra.arena")
_IS_MINEDOJO_AVAILABLE = _module_available("minedojo")
_IS_MINERL_AVAILABLE = _module_available("minerl")
_IS_TORCH_AVAILABLE = _module_available("torch")
