"""TensorBoard logging + shared run directory.

Reference behavior (``sheeprl/utils/logger.py``): rank-0 creates
``logs/runs/<root_dir>/<run_name>/version_k`` and broadcasts the resolved path
so all ranks agree; only rank 0 owns a writer. Here "rank" is the jax process
index; the broadcast uses multihost utils when multi-host, and is a no-op in
the common single-process SPMD case (one process drives all local chips).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np


class TensorBoardLogger:
    """Thin tensorboardX wrapper with the log-call surface the train loops use."""

    def __init__(self, log_dir: str):
        from tensorboardX import SummaryWriter

        self.log_dir = log_dir
        self._writer = SummaryWriter(log_dir)

    def log_metrics(self, metrics: Dict[str, Any], step: int) -> None:
        for name, value in metrics.items():
            if value is None:
                continue
            self._writer.add_scalar(name, float(np.asarray(value)), step)

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        import yaml

        text = yaml.safe_dump(params, sort_keys=False)
        self._writer.add_text("hparams", f"```yaml\n{text}\n```")

    def add_video(self, tag: str, video, step: int, fps: int = 30) -> None:
        self._writer.add_video(tag, video, step, fps=fps)

    def flush(self) -> None:
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()


def _next_version(parent: str) -> int:
    if not os.path.isdir(parent):
        return 0
    versions = [
        int(d.split("_")[1])
        for d in os.listdir(parent)
        if d.startswith("version_") and d.split("_")[1].isdigit()
    ]
    return max(versions) + 1 if versions else 0


def get_log_dir(cfg, root_dir: str, run_name: str, share: bool = True) -> str:
    """Resolve (and on process 0, create) the versioned run directory.

    Multi-host: process 0 picks ``version_k`` and broadcasts the path, exactly
    like the reference's rank-0 broadcast (logger.py:24-74).
    """
    base = os.path.join("logs", "runs", root_dir, run_name)
    if jax.process_index() == 0:
        log_dir = os.path.join(base, f"version_{_next_version(base)}")
        os.makedirs(log_dir, exist_ok=True)
    else:
        log_dir = ""
    if share and jax.process_count() > 1:
        from jax.experimental import multihost_utils

        buf = np.zeros(4096, dtype=np.uint8)
        if jax.process_index() == 0:
            encoded = log_dir.encode()
            buf[: len(encoded)] = np.frombuffer(encoded, dtype=np.uint8)
        buf = multihost_utils.broadcast_one_to_all(buf)
        log_dir = bytes(buf[buf != 0]).decode()
    return log_dir


def create_tensorboard_logger(cfg, exp_name: Optional[str] = None):
    """Build (logger, log_dir); logger is None off-process-0 or at log_level 0
    (reference logger.py:11-21)."""
    root_dir = cfg.root_dir if cfg.root_dir is not None else exp_name or "default"
    run_name = cfg.run_name
    log_dir = get_log_dir(cfg, root_dir, run_name)
    logger = None
    if jax.process_index() == 0 and cfg.metric.log_level > 0:
        logger = TensorBoardLogger(log_dir)
    # every algorithm resolves its run dir here, so this is where the run
    # telemetry learns where its trace / telemetry.json belong (no-op when
    # metric.telemetry is disabled)
    from sheeprl_tpu.obs.telemetry import get_telemetry

    telemetry = get_telemetry()
    if telemetry is not None:
        telemetry.attach_run_dir(log_dir)
    return logger, log_dir
