"""Named-scope wall-clock timing.

Same API as the reference global ``timer`` (``sheeprl/utils/timer.py:15-83``):
a context-decorator keyed by name into a class-level registry, globally
disable-able, with ``compute()`` returning accumulated seconds and resetting.
Train loops wrap the env-interaction and train phases; the CLI derives
``Time/sps_*`` rates from the ratios.

Thread safety: the decoupled algorithms time the player thread's env
interaction while the trainer thread calls ``compute()``/``reset()``, so the
registry is guarded by a lock and a scope that loses its entry to a
concurrent reset re-registers on exit instead of raising.

One TPU-specific caveat: jax dispatch is async, so a timed block that only
*launches* device work would under-report. Callers time around points where
they already synchronize (e.g. after pulling losses to host); ``timer`` itself
stays a pure wall-clock measure, matching the reference semantics.
"""

from __future__ import annotations

import threading
import time
from contextlib import ContextDecorator
from typing import Dict, Optional

from sheeprl_tpu.utils.metric import SumMetric


class timer(ContextDecorator):
    """``with timer("Time/train_time"): ...`` accumulates into a global registry."""

    disabled: bool = False
    timers: Dict[str, SumMetric] = {}
    _lock = threading.Lock()

    def __init__(self, name: str, metric: Optional[SumMetric] = None):
        self.name = name
        self._metric = metric
        if not timer.disabled:
            with timer._lock:
                if name not in timer.timers:
                    timer.timers[name] = (
                        metric if metric is not None else SumMetric(sync_on_compute=False)
                    )

    def __enter__(self):
        if not timer.disabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if not timer.disabled:
            elapsed = time.perf_counter() - self._start
            with timer._lock:
                if self.name not in timer.timers:  # registry was reset mid-scope
                    # a FRESH metric: re-registering the (possibly already
                    # computed) original would double count its total
                    sync = (
                        getattr(self._metric, "sync_on_compute", False)
                        if self._metric is not None
                        else False
                    )
                    timer.timers[self.name] = SumMetric(sync_on_compute=sync)
                timer.timers[self.name].update(elapsed)
        return False

    @classmethod
    def to(cls, device=None) -> None:  # pragma: no cover - reference-API shim
        pass

    @classmethod
    def compute(cls) -> Dict[str, float]:
        """Accumulated seconds per name; resets the registry (reference :60-76)."""
        with cls._lock:
            out = {name: metric.compute() for name, metric in cls.timers.items()}
            cls.timers = {}
        return out

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls.timers = {}
