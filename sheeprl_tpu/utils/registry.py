"""Algorithm / evaluation registries.

Mirrors the reference ``sheeprl/utils/registry.py`` (decorators at :88 and :95,
registry dicts at :11-12): decorating an entrypoint registers it under its
defining module, and importing :mod:`sheeprl_tpu` registers every built-in
algorithm as an import side effect (reference ``sheeprl/__init__.py:18-45``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

# {module_name: [{"name": algo_name, "entrypoint": fn_name, "decoupled": bool}]}
algorithm_registry: Dict[str, List[Dict[str, Any]]] = {}
evaluation_registry: Dict[str, List[Dict[str, Any]]] = {}


def _register_algorithm(fn: Callable, decoupled: bool = False) -> Callable:
    module = fn.__module__
    entrypoint = fn.__name__
    algos = algorithm_registry.setdefault(module, [])
    name = module.split(".")[-1]
    if any(a["name"] == name for a in algos):
        raise ValueError(f"Algorithm '{name}' already registered in module '{module}'")
    algos.append({"name": name, "entrypoint": entrypoint, "decoupled": decoupled})
    return fn


def _register_evaluation(fn: Callable, algorithms: Any) -> Callable:
    module = fn.__module__
    entrypoint = fn.__name__
    if isinstance(algorithms, str):
        algorithms = [algorithms]
    evals = evaluation_registry.setdefault(module, [])
    for algo in algorithms:
        evals.append({"name": algo, "entrypoint": entrypoint})
    return fn


def register_algorithm(decoupled: bool = False) -> Callable:
    """Decorator: register a ``main(fabric, cfg)`` training entrypoint."""

    def inner(fn: Callable) -> Callable:
        return _register_algorithm(fn, decoupled=decoupled)

    return inner


def register_evaluation(algorithms: Any) -> Callable:
    """Decorator: register an ``evaluate(fabric, cfg, state)`` entrypoint."""

    def inner(fn: Callable) -> Callable:
        return _register_evaluation(fn, algorithms)

    return inner


def find_algorithm(name: str) -> Optional[Dict[str, Any]]:
    """Look up a registered algorithm by name → {module, entrypoint, decoupled}."""
    for module, algos in algorithm_registry.items():
        for algo in algos:
            if algo["name"] == name:
                return {"module": module, **algo}
    return None


def find_evaluation(name: str) -> Optional[Dict[str, Any]]:
    for module, evals in evaluation_registry.items():
        for ev in evals:
            if ev["name"] == name:
                return {"module": module, **ev}
    return None


def registered_algorithm_names() -> List[str]:
    return sorted({a["name"] for algos in algorithm_registry.values() for a in algos})
