"""Model registry — publish trained agents out of training checkpoints.

Equivalent of the reference's model-manager subsystem (upstream sheeprl
ships ``sheeprl_model_manager.py`` → ``cli.registration`` backed by MLflow;
the mounted 0.4.7 snapshot contains only the shim, the newer test snapshot
exercises it via ``tests/conftest.py``). MLflow is not part of this image,
so the registry is filesystem-backed with the same concepts:

- **register**: copy the agent state out of a run checkpoint into
  ``<registry>/<name>/v<k>/`` together with the run's config and free-form
  metadata; versions auto-increment;
- **get / load**: resolve ``(name, version)`` → checkpoint path or restored
  pytree (latest version by default);
- **list / delete / transition**: enumerate the registry, drop versions,
  and move a version between ``none/staging/production`` stages.

Orbax is the storage format, so a registered model is loadable with the same
``Fabric.load`` used for training checkpoints.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

_STAGES = ("none", "staging", "production")


class ModelManager:
    def __init__(self, registry_dir: str = "models"):
        self.registry_dir = os.path.abspath(registry_dir)
        os.makedirs(self.registry_dir, exist_ok=True)

    # -- helpers -----------------------------------------------------------

    def _model_dir(self, name: str) -> str:
        return os.path.join(self.registry_dir, name)

    def _versions(self, name: str) -> List[int]:
        mdir = self._model_dir(name)
        if not os.path.isdir(mdir):
            return []
        out = []
        for d in os.listdir(mdir):
            if d.startswith("v") and d[1:].isdigit():
                out.append(int(d[1:]))
        return sorted(out)

    def _version_dir(self, name: str, version: int) -> str:
        return os.path.join(self._model_dir(name), f"v{version}")

    def _resolve(self, name: str, version: Optional[int]) -> int:
        versions = self._versions(name)
        if not versions:
            raise KeyError(f"No registered model named '{name}' in {self.registry_dir}")
        if version is None:
            return versions[-1]
        if version not in versions:
            raise KeyError(f"Model '{name}' has no version {version}; available: {versions}")
        return version

    # -- API ---------------------------------------------------------------

    def register_model(
        self,
        name: str,
        checkpoint_path: str,
        description: str = "",
        metadata: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Publish the checkpoint at ``checkpoint_path`` as a new version.

        The checkpoint directory (orbax tree) is copied verbatim; the run's
        persisted ``.hydra/config.yaml`` is copied alongside when present.
        Returns the new version number.
        """
        checkpoint_path = os.path.abspath(checkpoint_path)
        if not os.path.isdir(checkpoint_path):
            raise FileNotFoundError(f"Checkpoint not found: {checkpoint_path}")
        version = (self._versions(name)[-1] + 1) if self._versions(name) else 1
        vdir = self._version_dir(name, version)
        os.makedirs(vdir)
        shutil.copytree(checkpoint_path, os.path.join(vdir, "checkpoint"))
        run_cfg = os.path.join(
            os.path.dirname(os.path.dirname(checkpoint_path)), ".hydra", "config.yaml"
        )
        if os.path.isfile(run_cfg):
            shutil.copy(run_cfg, os.path.join(vdir, "config.yaml"))
        meta = {
            "name": name,
            "version": version,
            "description": description,
            "source_checkpoint": checkpoint_path,
            "registered_at": time.strftime("%Y-%m-%d %H:%M:%S"),
            "stage": "none",
            **(metadata or {}),
        }
        with open(os.path.join(vdir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
        return version

    def get_model(self, name: str, version: Optional[int] = None) -> str:
        """Path of the registered checkpoint (latest version by default)."""
        version = self._resolve(name, version)
        return os.path.join(self._version_dir(name, version), "checkpoint")

    def load_model(self, name: str, version: Optional[int] = None) -> Any:
        """Restore the registered agent pytree."""
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(self.get_model(name, version))

    def get_metadata(self, name: str, version: Optional[int] = None) -> Dict[str, Any]:
        version = self._resolve(name, version)
        with open(os.path.join(self._version_dir(name, version), "meta.json")) as f:
            return json.load(f)

    def list_models(self) -> Dict[str, List[Dict[str, Any]]]:
        out: Dict[str, List[Dict[str, Any]]] = {}
        if not os.path.isdir(self.registry_dir):
            return out
        for name in sorted(os.listdir(self.registry_dir)):
            versions = self._versions(name)
            if versions:
                out[name] = [self.get_metadata(name, v) for v in versions]
        return out

    def transition_model(self, name: str, version: Optional[int] = None, stage: str = "staging") -> None:
        """Move a version between lifecycle stages (MLflow-style)."""
        if stage not in _STAGES:
            raise ValueError(f"Unknown stage '{stage}'; must be one of {_STAGES}")
        version = self._resolve(name, version)
        path = os.path.join(self._version_dir(name, version), "meta.json")
        with open(path) as f:
            meta = json.load(f)
        meta["stage"] = stage
        with open(path, "w") as f:
            json.dump(meta, f, indent=2)

    def delete_model(self, name: str, version: Optional[int] = None) -> None:
        version = self._resolve(name, version)
        shutil.rmtree(self._version_dir(name, version))
        if not self._versions(name):
            shutil.rmtree(self._model_dir(name), ignore_errors=True)
