"""Command-line entry points.

Re-implementation of the reference ``sheeprl/cli.py`` (run :265-273,
run_algorithm :48-156, eval_algorithm :159-198, check_configs :201-257,
resume_from_checkpoint :22-45) on the mini-hydra config engine and the mesh
:class:`~sheeprl_tpu.fabric.Fabric`. One process drives every local device
(SPMD), so ``fabric.launch`` validates topology instead of spawning ranks.
"""

from __future__ import annotations

import importlib
import os
import sys
import warnings
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import sheeprl_tpu
from sheeprl_tpu.config.engine import compose, to_yaml
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import (
    algorithm_registry,
    evaluation_registry,
    find_algorithm,
    find_evaluation,
    registered_algorithm_names,
)
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import enable_persistent_compilation_cache, dotdict, print_config


def _load_run_config(ckpt_path: str):
    """Read the persisted config of the run that produced a checkpoint.

    Two layouts are recognized: training runs
    (``<log_dir>/checkpoint/ckpt_*`` with ``<log_dir>/.hydra/config.yaml``)
    and model-registry versions (``<registry>/<name>/v<k>/checkpoint`` with
    the config copied next to the checkpoint — utils/model_manager.py).
    Returns ``(cfg, log_dir)``."""
    import yaml

    ckpt_abs = os.path.abspath(ckpt_path)
    log_dir = os.path.dirname(os.path.dirname(ckpt_abs))
    candidates = [
        (os.path.join(log_dir, ".hydra", "config.yaml"), log_dir),
        (os.path.join(os.path.dirname(ckpt_abs), "config.yaml"), os.path.dirname(ckpt_abs)),
    ]
    for cfg_path, base in candidates:
        if os.path.isfile(cfg_path):
            with open(cfg_path) as f:
                return dotdict(yaml.safe_load(f)), base
    raise RuntimeError(
        f"Cannot use checkpoint {ckpt_path}: missing persisted config at any of "
        f"{[c for c, _ in candidates]}"
    )


def resume_from_checkpoint(cfg, overrides: Optional[Sequence[str]] = None) -> Any:
    """Merge the checkpoint run's persisted config into the current one
    (reference cli.py:22-45): the old config wins except for runtime keys.

    ``overrides`` is the raw CLI override list; the training horizon is only
    taken from the resuming command when it was *explicitly* overridden there,
    otherwise the checkpointed run's ``total_steps`` is preserved (a bare
    resume must not silently reset the horizon to the exp default)."""
    ckpt_path = cfg.checkpoint.resume_from
    old_cfg, _ = _load_run_config(ckpt_path)
    if old_cfg.env.id != cfg.env.id:
        raise ValueError(
            f"This experiment is run with a different environment from the one of the "
            f"checkpoint: got {cfg.env.id}, the checkpoint was trained on {old_cfg.env.id}"
        )
    if old_cfg.algo.name != cfg.algo.name:
        raise ValueError(
            f"This experiment is run with a different algorithm from the one of the "
            f"checkpoint: got {cfg.algo.name}, the checkpoint was trained with {old_cfg.algo.name}"
        )
    # keep the old experiment config, but let the new run control runtime keys
    old_cfg.checkpoint.resume_from = ckpt_path
    old_cfg.root_dir = cfg.root_dir
    old_cfg.run_name = cfg.run_name
    old_cfg.fabric = cfg.fabric
    # Re-apply every EXPLICIT value override from the resuming command on top
    # of the restored config. The restored config defines the experiment
    # (reference cli.py:22-45 swaps the config wholesale), but silently
    # dropping overrides the user typed is a trap: a round-5 diagnostic run
    # passed `algo.train_every=1e9 metric.log_level=0` on resume, both were
    # discarded, and the "no-training" probe trained at full cadence while
    # its config print (then emitted pre-merge) showed the overridden values.
    # Group SELECTIONS (exp=..., env=dmc) cannot be re-applied onto an
    # already-composed tree and keep their swap-time semantics; bare resumes
    # keep the checkpointed horizon (the counters carry progress either way).
    from sheeprl_tpu.config.engine import yaml_load

    reapplied = []
    dropped = []
    ignored = []  # (override, reason) — every typed token is accounted for
    for o in overrides or []:
        if o.startswith("~"):
            ignored.append(
                (o, "deletions cannot be re-applied onto the restored config")
            )
            continue
        if "=" not in o:
            ignored.append((o, "not a key=value override"))
            continue
        key, value = o.split("=", 1)
        added = key.startswith("+")
        key = key.lstrip("+")
        if key in ("checkpoint.resume_from", "root_dir", "run_name") or key.startswith("fabric"):
            continue  # already carried over above (not silent: cfg wins)
        if key == "exp":
            ignored.append(
                (o, "defaults-list selection, consumed at compose time; the "
                    "checkpointed experiment defines the recipe")
            )
            continue
        if "." not in key and isinstance(old_cfg.get(key, None), dict):
            ignored.append(
                (o, "group selection / dict-valued key with swap-time "
                    "semantics; it cannot be re-applied onto the composed "
                    f"tree — pass leaf overrides ({key}.<field>=...) to "
                    "change the restored section")
            )
            continue
        if not _set_existing_path(old_cfg, key, yaml_load(value), allow_new=added):
            # unknown key (typo, or a +new key the stored tree lacks):
            # inventing it would hide the misconfiguration this merge exists
            # to prevent — surface it instead
            dropped.append(o)
            continue
        reapplied.append(o)
    if reapplied or ignored:
        lines = [
            "resume_from_checkpoint: the restored config defines the "
            "experiment; typed overrides were accounted for as follows."
        ]
        if reapplied:
            lines.append(f"re-applied: {reapplied}.")
        for o, reason in ignored:
            lines.append(f"ignored {o!r}: {reason}.")
        warnings.warn(" ".join(lines))
    if dropped:
        raise ValueError(
            "resume_from_checkpoint: these overrides name keys absent from "
            f"the checkpointed config: {dropped}. For a typo'd key, fix the "
            "key; to add a new LEAF under an existing section, prefix with "
            "'+'; new nested sections cannot be added on a resume command."
        )
    return old_cfg


def _set_existing_path(cfg, key: str, value, allow_new: bool = False) -> bool:
    """Set ``key`` (dotted) in ``cfg`` only if the full path already exists
    (or ``allow_new`` and the PARENT exists). Returns False otherwise —
    never invents intermediate nodes, so typos don't silently no-op."""
    node = cfg
    parts = key.split(".")
    for p in parts[:-1]:
        if not isinstance(node, dict) or p not in node or not isinstance(node[p], dict):
            return False
        node = node[p]
    if not isinstance(node, dict):
        return False
    if parts[-1] not in node and not allow_new:
        return False
    node[parts[-1]] = value
    return True


def check_configs(cfg) -> None:
    """Strategy validation (reference cli.py:201-257)."""
    algo_name = cfg.algo.name
    entry = find_algorithm(algo_name)
    if entry is None:
        raise RuntimeError(
            f"Given the algorithm named '{algo_name}', no algorithm has been found to be imported. "
            f"Available algorithms: {registered_algorithm_names()}"
        )
    strategy = str(cfg.fabric.get("strategy", "auto"))
    if entry["decoupled"]:
        devices = cfg.fabric.get("devices", 1)
        if devices not in ("auto", -1) and int(devices) < 2:
            raise RuntimeError(
                f"The decoupled version of {algo_name} algorithm requires at least 2 devices: "
                "one player and at least one trainer. "
                f"Please set `fabric.devices` to at least 2, got {devices}"
            )
    elif strategy not in ("auto", "ddp", "dp"):
        warnings.warn(
            f"Running an algorithm with a strategy ('{strategy}') "
            "different than 'auto'/'ddp': on TPU every strategy maps to SPMD "
            "data-parallel over the mesh",
            UserWarning,
        )
    if cfg.metric.get("log_level", 1) > 0 and len(cfg.metric.get("aggregator", {}).get("metrics", {})) == 0:
        warnings.warn(
            "No metrics defined in metric.aggregator.metrics: nothing will be aggregated",
            UserWarning,
        )

    # in-run eval (eval.every_n_steps, sheeprl_tpu/evals/inrun) is wired into
    # the coupled SAC and Dreamer loops; elsewhere the knob would silently do
    # nothing — the silent-ignore trap the resume-override accounting closes
    if int((cfg.get("eval", {}) or {}).get("every_n_steps", 0) or 0) > 0 and algo_name not in (
        "sac",
        "dreamer_v1",
        "dreamer_v2",
        "dreamer_v3",
    ):
        warnings.warn(
            f"eval.every_n_steps={cfg.eval.every_n_steps} is only consumed by "
            f"the coupled SAC and Dreamer (v1/v2/v3) entrypoints for now; "
            f"'{algo_name}' runs without in-run eval (howto/evaluation.md)",
            UserWarning,
        )

    # fused recurrent-core kernels (algo.fused_kernels, sheeprl_tpu/kernels):
    # a `pallas` request on a non-TPU backend is not an error — the registry
    # degrades it to the padded-XLA tier at agent-build time — but say so
    # here, up front, instead of only counting it in telemetry
    from sheeprl_tpu.kernels import normalize_tier

    fused_req = normalize_tier(cfg.algo.get("fused_kernels", "off"))
    if fused_req != "off" and algo_name not in (
        "dreamer_v1",
        "dreamer_v2",
        "p2e_dv1_exploration",
        "p2e_dv1_finetuning",
        "p2e_dv2_exploration",
        "p2e_dv2_finetuning",
    ):
        warnings.warn(
            f"algo.fused_kernels={cfg.algo.fused_kernels} is only consumed by "
            f"the dreamer-v1/v2 recurrent cores (and their P2E variants); "
            f"'{algo_name}' ignores it (howto/kernels.md)",
            UserWarning,
        )
    elif fused_req == "pallas":
        import jax

        if jax.default_backend() != "tpu":
            warnings.warn(
                f"algo.fused_kernels=pallas on backend={jax.default_backend()}: "
                "the Pallas kernels target TPU — the run will auto-degrade to "
                "the padded-XLA tier (counted as kernel_tier_degraded in "
                "telemetry; howto/kernels.md)",
                UserWarning,
            )

    # the actor–learner plane (plane.*, sheeprl_tpu/plane) is consumed by the
    # decoupled entrypoints only; validate its knobs here so a multi-process
    # run can't silently degrade (mirrors the env.act_burst rule above)
    num_players = int(cfg.get("plane", {}).get("num_players", 0) or 0)
    if num_players > 0:
        if not entry["decoupled"]:
            warnings.warn(
                f"plane.num_players={num_players} is only consumed by the "
                f"decoupled entrypoints (sac_decoupled, ppo_decoupled); "
                f"'{algo_name}' runs coupled and ignores it "
                "(howto/actor_learner.md)",
                UserWarning,
            )
        elif str(cfg.env.get("vectorization", "") or "").lower() == "sync" or (
            # the legacy spelling resolves to the same sync backend when
            # vectorization is unset (envs/vector/factory.resolve_vectorization)
            cfg.env.get("vectorization", None) is None
            and bool(cfg.env.get("sync_env", None))
        ):
            raise RuntimeError(
                f"plane.num_players={num_players} with a sync env pool "
                "(env.vectorization=sync, or legacy env.sync_env=true) "
                "serializes every player's env fleet inside its own process — "
                "the degraded pool defeats the multi-process plane. Drop the "
                "sync override (players default to the shared-memory async "
                "pool) or set plane.num_players=0 (howto/actor_learner.md)."
            )
        keep = int(cfg.get("plane", {}).get("keep_policies", 4) or 4)
        if keep < 2:
            raise RuntimeError(
                f"plane.keep_policies={keep} can garbage-collect the policy "
                "version a freshly-respawned player still needs; use >= 2 "
                "(howto/actor_learner.md)"
            )

    # mixed precision is validated for everyone but currently consumed only by
    # the DreamerV3 model family — warn instead of silently training in f32
    from sheeprl_tpu.fabric import compute_dtype_from_precision

    precision = cfg.fabric.get("precision", "32-true")
    if compute_dtype_from_precision(precision) is not None and algo_name not in (
        "dreamer_v3",
        "p2e_dv3_exploration",
        "p2e_dv3_finetuning",
    ):
        warnings.warn(
            f"fabric.precision={precision} is only consumed by the DreamerV3 model "
            f"family; '{algo_name}' will train in f32",
            UserWarning,
        )


def _prune_metric_keys(cfg, algo_module: str) -> None:
    """Drop aggregator keys the algorithm never updates (reference cli.py:141-155)."""
    try:
        utils_module = importlib.import_module(f"{algo_module.rsplit('.', 1)[0]}.utils")
        keys = getattr(utils_module, "AGGREGATOR_KEYS", None)
    except ModuleNotFoundError:
        keys = None
    if keys is None:
        return
    metrics_cfg = cfg.metric.get("aggregator", {}).get("metrics", {})
    for name in list(metrics_cfg.keys()):
        if name not in keys:
            metrics_cfg.pop(name)


def _load_exploration_cfg(cfg) -> Any:
    """P2E finetuning: re-read the exploration run's persisted config and
    inherit its env settings (reference cli.py:106-137)."""
    ckpt_path = cfg.checkpoint.exploration_ckpt_path
    if not ckpt_path:
        raise ValueError(
            "P2E finetuning requires checkpoint.exploration_ckpt_path pointing at an "
            "exploration-phase checkpoint"
        )
    exploration_cfg, _ = _load_run_config(ckpt_path)
    if exploration_cfg.env.id != cfg.env.id:
        raise ValueError(
            "This experiment is run with a different environment from "
            "the one of the exploration you want to finetune. "
            f"Got '{cfg.env.id}', but the environment used during exploration was "
            f"{exploration_cfg.env.id}. Set properly the environment for finetuning "
            "the experiment."
        )
    # Take environment configs from exploration
    for k in (
        "frame_stack",
        "screen_size",
        "action_repeat",
        "grayscale",
        "clip_rewards",
        "frame_stack_dilation",
        "max_episode_steps",
        "reward_as_observation",
    ):
        if k in exploration_cfg.env:
            cfg.env[k] = exploration_cfg.env[k]
    return exploration_cfg


def run_algorithm(cfg) -> None:
    """Registry lookup → Fabric → entrypoint (reference cli.py:48-156)."""
    entry = find_algorithm(cfg.algo.name)
    if entry is None:
        raise RuntimeError(
            f"Given the algorithm named '{cfg.algo.name}', no algorithm has been found to be imported. "
            f"Available algorithms: {registered_algorithm_names()}"
        )
    module = importlib.import_module(entry["module"])
    entrypoint = getattr(module, entry["entrypoint"])

    kwargs = {}
    if "finetuning" in cfg.algo.name and "p2e" in entry["module"]:
        kwargs["exploration_cfg"] = _load_exploration_cfg(cfg)

    # parallel group → Fabric sharding knobs (the {'data','model'} mesh);
    # absent/empty group keeps the pure data-parallel defaults.
    parallel_cfg = cfg.get("parallel", None) or {}
    fabric = instantiate(
        cfg.fabric,
        model_axis=parallel_cfg.get("model_axis", 1) or 1,
        shard_min_bytes=parallel_cfg.get("shard_min_bytes", None),
        shard_overrides=parallel_cfg.get("shard_overrides", None),
    )

    # Observability gates (reference cli.py:141-155)
    _prune_metric_keys(cfg, entry["module"])
    MetricAggregator.disabled = cfg.metric.log_level == 0 or len(
        cfg.metric.get("aggregator", {}).get("metrics", {})
    ) == 0
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.get("disable_timer", False)

    # Run telemetry (metric.telemetry config group, obs/): spans, counters,
    # health guards. Owned here so the end-of-run summary/telemetry.json is
    # written even when the entrypoint raises; the run dir is attached later
    # by create_tensorboard_logger once the versioned path exists.
    from sheeprl_tpu.obs.telemetry import finalize_telemetry, setup_telemetry

    # Checkpoint subsystem (checkpoint config group, ckpt/): async saver,
    # keep-policy GC, SIGTERM/SIGINT preemption capture. Torn down in the
    # same finally so an in-flight async save is drained before the process
    # exits (and before telemetry finalizes, so its counters are complete).
    from sheeprl_tpu.ckpt import setup_checkpoint, teardown_checkpoint

    setup_telemetry(cfg)
    setup_checkpoint(cfg)
    try:
        # jax.profiler trace capture around the whole run (SURVEY §5.1 — the
        # TPU superset of the reference's named-scope timers)
        profiler = cfg.metric.get("profiler", False)
        if profiler:
            import jax

            # traces land inside the run tree next to checkpoints/metrics
            trace_dir = (
                profiler
                if isinstance(profiler, str)
                else os.path.join(
                    "logs", "runs", str(cfg.root_dir), str(cfg.run_name), "jax_traces"
                )
            )
            with jax.profiler.trace(os.path.abspath(trace_dir)):
                return fabric.launch(entrypoint, cfg, **kwargs)

        fabric.launch(entrypoint, cfg, **kwargs)
    finally:
        teardown_checkpoint()
        # inside a finally, exc_info() sees the in-flight exception (if any):
        # a crashed run's telemetry.json records `"crashed": true` plus the
        # exception type next to the partial counters
        finalize_telemetry(error=sys.exc_info()[1])


def eval_algorithm(cfg) -> None:
    """Load checkpoint state and dispatch the evaluation fn (cli.py:159-198)."""
    entry = find_evaluation(cfg.algo.name)
    if entry is None:
        raise RuntimeError(
            f"Given the algorithm named '{cfg.algo.name}', no evaluation function has been found"
        )
    module = importlib.import_module(entry["module"])
    entrypoint = getattr(module, entry["entrypoint"])

    cfg.fabric.devices = 1
    fabric = instantiate(cfg.fabric)
    state = fabric.load(cfg.checkpoint_path)
    fabric.launch(entrypoint, cfg, state)


def _compose_from_argv(args: Optional[Sequence[str]], **kwargs) -> Any:
    overrides = list(args) if args is not None else sys.argv[1:]
    return compose("config", overrides=overrides, **kwargs)


def run(args: Optional[Sequence[str]] = None) -> None:
    """Train entrypoint (reference cli.py:265-273).

    ``-m``/``--multirun`` enables the Hydra-basic-sweeper subset (reference
    CLI inherits it from ``@hydra.main``, hydra 1.3): comma-separated
    override values expand to the cartesian product and the jobs run
    sequentially in-process, like Hydra's default launcher. Distinct output
    dirs come from the logger's ``version_k`` auto-increment.
    """
    overrides = list(args) if args is not None else sys.argv[1:]
    if "-m" in overrides or "--multirun" in overrides:
        from sheeprl_tpu.config.engine import expand_multirun

        overrides = [o for o in overrides if o not in ("-m", "--multirun")]
        jobs = expand_multirun(overrides)
        if len(jobs) > 1:
            for i, job in enumerate(jobs):
                print(f"[multirun] job {i + 1}/{len(jobs)}: {' '.join(job)}", flush=True)
                run(job)
            return
        # single job: fall through to the normal path with the cleaned argv
        args = overrides
    enable_persistent_compilation_cache()
    cfg = _compose_from_argv(args)
    if int(cfg.fabric.get("num_nodes", 1)) > 1:
        # must precede any backend initialization (fabric device queries,
        # algorithm imports that build jit caches, ...)
        from sheeprl_tpu.fabric import init_distributed

        init_distributed()
    sheeprl_tpu.register_algorithms()
    if cfg.checkpoint.resume_from:
        # `latest` (or a run-dir path) resolves to the newest manifest-valid
        # checkpoint BEFORE the config merge, which needs a concrete path
        from sheeprl_tpu.ckpt import resolve_resume_from

        cfg.checkpoint.resume_from = resolve_resume_from(cfg)
        cfg = resume_from_checkpoint(cfg, overrides)
    # print AFTER the resume merge so the tree shown is the effective config
    # (printing pre-merge showed override values the merge then discarded)
    if cfg.metric.log_level > 0:
        print_config(cfg)
    check_configs(cfg)
    run_algorithm(cfg)


def evaluation(args: Optional[Sequence[str]] = None) -> None:
    """Eval entrypoint (reference cli.py:276-312): re-reads the run's persisted
    config, forces a single-device single-env setup, and keeps the seed."""
    enable_persistent_compilation_cache()
    sheeprl_tpu.register_algorithms()
    overrides = list(args) if args is not None else sys.argv[1:]
    # the eval CLI takes checkpoint_path=... plus optional fabric overrides
    eval_cfg = compose(
        "eval_config",
        overrides=overrides,
        allow_missing=("checkpoint_path",),
    )
    ckpt_path = eval_cfg.get("checkpoint_path")
    if not ckpt_path or ckpt_path == "???":
        raise ValueError("You must specify the checkpoint path: checkpoint_path=/path/to/ckpt")
    # `registry:best:<algo>:<env id>` → the model registry's best record
    # (evals/registry.py; deterministic mean/n/append-order resolution).
    # Same resolver the serving gateway uses (sheeprl_tpu/serve).
    from sheeprl_tpu.evals.registry import resolve_checkpoint_ref

    ckpt_path, record = resolve_checkpoint_ref(
        ckpt_path,
        str((eval_cfg.get("eval", {}) or {}).get("registry_dir", "logs/registry")),
    )
    if record is not None:
        print(
            f"[registry] best {record.get('algo')} on {record.get('env')}: "
            f"{ckpt_path} (mean {record.get('metrics', {}).get('mean')})"
        )
    cfg, log_dir = _load_run_config(ckpt_path)
    # eval-time service knobs come from the eval CLI's composed `eval` group
    # (the run's persisted knobs configured its own in-run eval, not this
    # re-score); missing keys fall back to the shipped defaults
    from sheeprl_tpu.evals.service import eval_settings

    cfg["eval"] = eval_settings(eval_cfg)

    cfg.run_name = os.path.join(
        os.path.basename(log_dir), f"evaluation_{np.random.randint(0, 2**16)}"
    )
    cfg.env.num_envs = 1
    cfg.env.capture_video = bool(eval_cfg.get("env", {}).get("capture_video", cfg.env.capture_video))
    # keep the run's PRNG implementation at eval time (a threefry-trained
    # run should not sample under the constructor-default rbg)
    run_fabric = cfg.get("fabric", {}) or {}
    cfg.fabric = dotdict(
        {
            "_target_": "sheeprl_tpu.fabric.Fabric",
            "devices": 1,
            "num_nodes": 1,
            "strategy": "auto",
            "accelerator": eval_cfg.get("fabric", {}).get("accelerator", "auto"),
            "precision": eval_cfg.get("fabric", {}).get("precision", "32-true"),
            "prng_impl": run_fabric.get("prng_impl", "rbg"),
            "callbacks": [],
        }
    )
    cfg.checkpoint_path = ckpt_path
    eval_algorithm(cfg)


def serve(args: Optional[Sequence[str]] = None) -> None:
    """Serving entrypoint (sheeprl_tpu/serve, howto/serving.md): load a
    checkpoint (or ``registry:best:`` ref) through the eval-builder registry
    and serve batched ``act(obs)`` inference with request coalescing,
    hot-swap, and a SIGTERM drain."""
    enable_persistent_compilation_cache()
    sheeprl_tpu.register_algorithms()
    overrides = list(args) if args is not None else sys.argv[1:]
    serve_cfg = compose(
        "serve_config",
        overrides=overrides,
        allow_missing=("checkpoint_path",),
    )
    ckpt_path = serve_cfg.get("checkpoint_path")
    if not ckpt_path or ckpt_path == "???":
        raise ValueError("You must specify the checkpoint path: checkpoint_path=/path/to/ckpt")
    from sheeprl_tpu.serve.gateway import run_serve_entrypoint

    run_serve_entrypoint(serve_cfg)


def registration(args: Optional[Sequence[str]] = None) -> None:
    """Model-registration entrypoint (upstream sheeprl's
    ``sheeprl_model_manager.py`` → ``cli.registration``): publish a training
    checkpoint into the filesystem model registry."""
    from sheeprl_tpu.utils.model_manager import ModelManager

    overrides = list(args) if args is not None else sys.argv[1:]
    cfg = compose(
        "model_manager_config",
        overrides=overrides,
        allow_missing=("checkpoint_path", "model_name"),
    )
    ckpt_path = cfg.get("checkpoint_path")
    model_name = cfg.get("model_name")
    if not ckpt_path or ckpt_path == "???":
        raise ValueError("You must specify the checkpoint path: checkpoint_path=/path/to/ckpt")
    if not model_name or model_name == "???":
        raise ValueError("You must specify the model name: model_name=my_agent")
    manager = ModelManager(cfg.get("registry_dir", "models"))
    version = manager.register_model(
        model_name, ckpt_path, description=cfg.get("description", "")
    )
    print(f"Registered '{model_name}' v{version} in {manager.registry_dir}")


if __name__ == "__main__":
    run()
