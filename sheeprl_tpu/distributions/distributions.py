"""Jit-friendly probability distributions.

Ground-up jnp implementation of the reference probability layer
(``sheeprl/utils/distribution.py``: TruncatedNormal :116, SymlogDistribution
:152, MSEDistribution :196, TwoHotEncodingDistribution :224,
OneHotCategorical(+StraightThrough) :277-395, KL registration :398) plus the
Normal/TanhNormal machinery the SAC family needs (reference uses
torch.distributions directly there).

Every distribution is an immutable pytree-of-arrays with pure methods, so a
distribution can be constructed *inside* a jitted train step and traced away —
there is no object overhead at runtime, just fused elementwise math. Sampling
takes an explicit PRNG key (threaded from the step's key), which is what makes
seeds-to-bitwise reproducibility hold under jit.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)


def symlog(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class Distribution:
    """Minimal protocol: log_prob / sample / rsample / mean / mode / entropy."""

    def sample(self, seed: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jnp.ndarray:
        raise NotImplementedError

    def rsample(self, seed: jax.Array, sample_shape: Tuple[int, ...] = ()) -> jnp.ndarray:
        raise NotImplementedError

    def log_prob(self, value: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def entropy(self) -> jnp.ndarray:
        raise NotImplementedError


class Normal(Distribution):
    def __init__(self, loc: jnp.ndarray, scale: jnp.ndarray, validate_args: Optional[bool] = None):
        self.loc = jnp.asarray(loc)
        self.scale = jnp.asarray(scale)

    @property
    def mean(self) -> jnp.ndarray:
        return self.loc

    @property
    def mode(self) -> jnp.ndarray:
        return self.loc

    @property
    def stddev(self) -> jnp.ndarray:
        return self.scale

    def sample(self, seed, sample_shape=()):
        shape = tuple(sample_shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(seed, shape, dtype=self.loc.dtype)
        return jax.lax.stop_gradient(self.loc + self.scale * eps)

    def rsample(self, seed, sample_shape=()):
        shape = tuple(sample_shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(seed, shape, dtype=self.loc.dtype)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        var = self.scale**2
        return -((value - self.loc) ** 2) / (2 * var) - jnp.log(self.scale) - _HALF_LOG_2PI

    def entropy(self):
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)


class Independent(Distribution):
    """Sum log-probs/entropy over the last ``reinterpreted_batch_ndims`` dims."""

    def __init__(self, base: Distribution, reinterpreted_batch_ndims: int = 1, validate_args=None):
        self.base = base
        self.ndims = reinterpreted_batch_ndims

    def _reduce(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.ndims == 0:
            return x
        return jnp.sum(x, axis=tuple(range(-self.ndims, 0)))

    @property
    def mean(self):
        return self.base.mean

    @property
    def mode(self):
        return self.base.mode

    def sample(self, seed, sample_shape=()):
        return self.base.sample(seed, sample_shape)

    def rsample(self, seed, sample_shape=()):
        return self.base.rsample(seed, sample_shape)

    def log_prob(self, value):
        return self._reduce(self.base.log_prob(value))

    def entropy(self):
        return self._reduce(self.base.entropy())


class TanhNormal(Distribution):
    """tanh-squashed Normal with the exact log-det-Jacobian correction.

    The SAC actor (reference sac/agent.py:106-138 squashes a Normal and
    subtracts ``log(1 - tanh(u)^2)``); here the correction uses the
    numerically-stable ``2*(log2 - u - softplus(-2u))`` form.
    """

    def __init__(self, loc: jnp.ndarray, scale: jnp.ndarray):
        self.base = Normal(loc, scale)

    @property
    def mean(self):
        return jnp.tanh(self.base.mean)

    @property
    def mode(self):
        return jnp.tanh(self.base.mode)

    def sample_and_log_prob(self, seed, sample_shape=()):
        u = self.base.rsample(seed, sample_shape)
        a = jnp.tanh(u)
        log_prob = self.base.log_prob(u) - 2.0 * (
            math.log(2.0) - u - jax.nn.softplus(-2.0 * u)
        )
        return a, log_prob

    def rsample(self, seed, sample_shape=()):
        return jnp.tanh(self.base.rsample(seed, sample_shape))

    def sample(self, seed, sample_shape=()):
        return jax.lax.stop_gradient(self.rsample(seed, sample_shape))

    def log_prob(self, value):
        # atanh with clipping for numerical safety at the boundary
        value = jnp.clip(value, -1.0 + 1e-6, 1.0 - 1e-6)
        u = jnp.arctanh(value)
        return self.base.log_prob(u) - 2.0 * (math.log(2.0) - u - jax.nn.softplus(-2.0 * u))


# ---------------------------------------------------------------------------
# truncated normal (reference distribution.py:25-147)
# ---------------------------------------------------------------------------


def _std_normal_cdf(x):
    return 0.5 * (1 + jax.lax.erf(x / math.sqrt(2.0)))


def _std_normal_icdf(p):
    return math.sqrt(2.0) * jax.lax.erf_inv(2 * p - 1)


def _std_normal_pdf(x):
    return jnp.exp(-0.5 * x * x) / math.sqrt(2 * math.pi)


class TruncatedNormal(Distribution):
    """Normal(loc, scale) truncated to ``[low, high]`` with analytic
    cdf/icdf/log_prob/entropy and inverse-cdf reparameterized sampling
    (reference TruncatedStandardNormal/TruncatedNormal, distribution.py:25-147).
    Used by the Dreamer continuous actors.
    """

    def __init__(self, loc, scale, low=-1.0, high=1.0, validate_args=None):
        self.loc = jnp.asarray(loc)
        self.scale = jnp.asarray(scale)
        self.low = jnp.asarray(low, dtype=self.loc.dtype)
        self.high = jnp.asarray(high, dtype=self.loc.dtype)
        self.alpha = (self.low - self.loc) / self.scale
        self.beta = (self.high - self.loc) / self.scale
        self.cdf_alpha = _std_normal_cdf(self.alpha)
        self.Z = jnp.clip(_std_normal_cdf(self.beta) - self.cdf_alpha, 1e-8, None)

    @property
    def mean(self):
        num = _std_normal_pdf(self.alpha) - _std_normal_pdf(self.beta)
        return self.loc + self.scale * num / self.Z

    @property
    def mode(self):
        return jnp.clip(self.loc, self.low, self.high)

    def cdf(self, value):
        xi = (value - self.loc) / self.scale
        return jnp.clip((_std_normal_cdf(xi) - self.cdf_alpha) / self.Z, 0.0, 1.0)

    def icdf(self, p):
        return self.loc + self.scale * _std_normal_icdf(self.cdf_alpha + p * self.Z)

    def rsample(self, seed, sample_shape=()):
        shape = tuple(sample_shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        u = jax.random.uniform(seed, shape, dtype=self.loc.dtype, minval=1e-6, maxval=1 - 1e-6)
        return jnp.clip(self.icdf(u), self.low, self.high)

    def sample(self, seed, sample_shape=()):
        return jax.lax.stop_gradient(self.rsample(seed, sample_shape))

    def log_prob(self, value):
        xi = (value - self.loc) / self.scale
        log_p = -0.5 * xi * xi - _HALF_LOG_2PI - jnp.log(self.scale) - jnp.log(self.Z)
        inside = (value >= self.low) & (value <= self.high)
        return jnp.where(inside, log_p, -jnp.inf)

    def entropy(self):
        a_pdf = _std_normal_pdf(self.alpha)
        b_pdf = _std_normal_pdf(self.beta)
        # lim x->±inf x*pdf(x) = 0
        a_term = jnp.where(jnp.isfinite(self.alpha), self.alpha * a_pdf, 0.0)
        b_term = jnp.where(jnp.isfinite(self.beta), self.beta * b_pdf, 0.0)
        return (
            0.5
            + _HALF_LOG_2PI
            + jnp.log(self.scale * self.Z)
            + (a_term - b_term) / (2 * self.Z)
        )


# ---------------------------------------------------------------------------
# dreamer "distributions": negative errors as log_prob
# ---------------------------------------------------------------------------


class SymlogDistribution(Distribution):
    """log_prob = −(symlog-space error); mode/mean = symexp(pred)
    (reference distribution.py:152-193) — the DV3 vector-obs decoder head."""

    def __init__(self, mode: jnp.ndarray, dims: int = 1, dist: str = "mse", agg: str = "sum"):
        self._mode = mode
        self._dims = dims
        self._dist = dist
        self._agg = agg

    @property
    def mode(self):
        return symexp(self._mode)

    @property
    def mean(self):
        return symexp(self._mode)

    def log_prob(self, value):
        target = symlog(value)
        if self._dist == "mse":
            distance = (self._mode - target) ** 2
        elif self._dist == "abs":
            distance = jnp.abs(self._mode - target)
        else:
            raise ValueError(f"Unknown distance '{self._dist}'")
        if self._agg == "sum":
            loss = jnp.sum(distance, axis=tuple(range(-self._dims, 0)))
        else:
            loss = jnp.mean(distance, axis=tuple(range(-self._dims, 0)))
        return -loss


class MSEDistribution(Distribution):
    """log_prob = −MSE (reference distribution.py:196-221) — the DV3 pixel decoder."""

    def __init__(self, mode: jnp.ndarray, dims: int = 3, agg: str = "sum"):
        self._mode = mode
        self._dims = dims
        self._agg = agg

    @property
    def mode(self):
        return self._mode

    @property
    def mean(self):
        return self._mode

    def log_prob(self, value):
        distance = (self._mode - value) ** 2
        if self._agg == "sum":
            loss = jnp.sum(distance, axis=tuple(range(-self._dims, 0)))
        else:
            loss = jnp.mean(distance, axis=tuple(range(-self._dims, 0)))
        return -loss


class TwoHotEncodingDistribution(Distribution):
    """255-bin two-hot over symlog space (reference distribution.py:224-272).

    ``mean``/``mode`` are ``symexp`` of the expected bin; ``log_prob`` is the
    cross-entropy against the two-hot encoding of ``symlog(value)``. The DV3
    reward head and critic.
    """

    def __init__(
        self,
        logits: jnp.ndarray,
        dims: int = 1,
        low: float = -20.0,
        high: float = 20.0,
        transfwd=symlog,
        transbwd=symexp,
    ):
        if logits.shape[-1] < 2:
            raise ValueError(
                "TwoHotEncodingDistribution needs at least 2 bins to place "
                f"probability mass between bin edges, got {logits.shape[-1]}"
            )
        self.logits = logits
        self._dims = dims
        self.bins = jnp.linspace(low, high, logits.shape[-1], dtype=logits.dtype)
        self._low, self._high = float(low), float(high)
        self._step = (float(high) - float(low)) / (logits.shape[-1] - 1)
        self.transfwd = transfwd
        self.transbwd = transbwd

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mean(self):
        value = jnp.sum(self.probs * self.bins, axis=-1, keepdims=True)
        return self.transbwd(value)

    @property
    def mode(self):
        return self.mean

    def _two_hot(self, x: jnp.ndarray) -> jnp.ndarray:
        n_bins = self.bins.shape[0]
        # The bins are uniform in transformed (symlog) space, so the
        # searchsorted is pure index arithmetic — on TPU this replaces a
        # binary-search while-loop plus two bin gathers (~4 ms/step of the
        # DV3 train program, 20% of the whole step) with elementwise VPU ops.
        pos = (jnp.clip(x, self._low, self._high) - self._low) / self._step
        above = jnp.clip(jnp.ceil(pos).astype(jnp.int32), 1, n_bins - 1)
        below = above - 1
        w_above = jnp.clip(pos - below.astype(x.dtype), 0.0, 1.0)
        w_below = 1.0 - w_above
        return (
            jax.nn.one_hot(below, n_bins, dtype=x.dtype) * w_below[..., None]
            + jax.nn.one_hot(above, n_bins, dtype=x.dtype) * w_above[..., None]
        )

    def log_prob(self, value):
        # value: [..., 1]; squeeze the trailing scalar dim for binning
        x = self.transfwd(value)[..., 0]
        target = self._two_hot(x)
        log_pred = jax.nn.log_softmax(self.logits, axis=-1)
        ll = jnp.sum(target * log_pred, axis=-1, keepdims=True)
        if self._dims:
            ll = jnp.sum(ll, axis=tuple(range(-self._dims, 0)))
        return ll


# ---------------------------------------------------------------------------
# categorical family
# ---------------------------------------------------------------------------


class OneHotCategorical(Distribution):
    """One-hot categorical over the last axis (reference distribution.py:277-379)."""

    def __init__(self, logits: Optional[jnp.ndarray] = None, probs: Optional[jnp.ndarray] = None,
                 validate_args: Optional[bool] = None):
        if (logits is None) == (probs is None):
            raise ValueError("Provide exactly one of logits / probs")
        if logits is None:
            probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
            logits = jnp.log(jnp.clip(probs, 1e-12, None))
        self.logits = jax.nn.log_softmax(logits, axis=-1)

    @property
    def probs(self):
        return jnp.exp(self.logits)

    @property
    def num_classes(self) -> int:
        return self.logits.shape[-1]

    @property
    def mean(self):
        return self.probs

    @property
    def mode(self):
        return jax.nn.one_hot(jnp.argmax(self.logits, axis=-1), self.num_classes, dtype=self.logits.dtype)

    def sample(self, seed, sample_shape=()):
        shape = tuple(sample_shape) + self.logits.shape[:-1]
        idx = jax.random.categorical(seed, self.logits, axis=-1, shape=shape)
        return jax.nn.one_hot(idx, self.num_classes, dtype=self.logits.dtype)

    def log_prob(self, value):
        return jnp.sum(value * self.logits, axis=-1)

    def entropy(self):
        return -jnp.sum(self.probs * self.logits, axis=-1)


class OneHotCategoricalStraightThrough(OneHotCategorical):
    """Straight-through gradient sampling:
    ``rsample = sample + probs − sg(probs)`` (reference distribution.py:382-395)."""

    def rsample(self, seed, sample_shape=()):
        s = self.sample(seed, sample_shape)
        probs = self.probs
        return s + probs - jax.lax.stop_gradient(probs)


class Bernoulli(Distribution):
    """Independent Bernoulli with logits — the Dreamer continue head."""

    def __init__(self, logits: jnp.ndarray, validate_args: Optional[bool] = None):
        self.logits = jnp.asarray(logits)

    @property
    def probs(self):
        return jax.nn.sigmoid(self.logits)

    @property
    def mean(self):
        return self.probs

    @property
    def mode(self):
        return (self.logits > 0).astype(self.logits.dtype)

    def sample(self, seed, sample_shape=()):
        shape = tuple(sample_shape) + self.logits.shape
        u = jax.random.uniform(seed, shape)
        return (u < self.probs).astype(self.logits.dtype)

    def log_prob(self, value):
        return -(
            jax.nn.softplus(-self.logits) * value + jax.nn.softplus(self.logits) * (1.0 - value)
        )

    def entropy(self):
        p = self.probs
        return jax.nn.softplus(self.logits) - self.logits * p


def kl_divergence(p: Distribution, q: Distribution) -> jnp.ndarray:
    """KL(p ‖ q). Categorical↔categorical is what the Dreamer KL balance needs
    (reference registers the OneHot pair at distribution.py:398-400)."""
    if isinstance(p, Independent) and isinstance(q, Independent):
        if p.ndims != q.ndims:
            raise ValueError("Independent KL requires matching reinterpreted dims")
        inner = kl_divergence(p.base, q.base)
        return jnp.sum(inner, axis=tuple(range(-p.ndims, 0))) if p.ndims else inner
    if isinstance(p, OneHotCategorical) and isinstance(q, OneHotCategorical):
        return jnp.sum(jnp.exp(p.logits) * (p.logits - q.logits), axis=-1)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    raise NotImplementedError(f"KL not implemented for {type(p).__name__} / {type(q).__name__}")
