from sheeprl_tpu.distributions.distributions import (
    Bernoulli,
    Independent,
    MSEDistribution,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
    kl_divergence,
)

__all__ = [
    "Bernoulli",
    "Independent",
    "MSEDistribution",
    "Normal",
    "OneHotCategorical",
    "OneHotCategoricalStraightThrough",
    "SymlogDistribution",
    "TanhNormal",
    "TruncatedNormal",
    "TwoHotEncodingDistribution",
    "kl_divergence",
]
