"""Print every registered algorithm and its evaluation entrypoint
(reference ``sheeprl/available_agents.py``):

    python -m sheeprl_tpu.available_agents
"""

if __name__ == "__main__":
    from rich.console import Console
    from rich.table import Table

    import sheeprl_tpu
    from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry

    sheeprl_tpu.register_algorithms()

    table = Table(title="SheepRL-TPU Agents")
    table.add_column("Module")
    table.add_column("Algorithm")
    table.add_column("Entrypoint")
    table.add_column("Decoupled")
    table.add_column("Evaluated by")

    for module, implementations in algorithm_registry.items():
        for algo in implementations:
            evaluation_entrypoint = "Undefined"
            # evaluations register under their own module (the evaluate file);
            # match by algorithm name across the whole evaluation registry
            for ev_module, evaluations in evaluation_registry.items():
                for evaluation in evaluations:
                    if algo["name"] == evaluation["name"]:
                        evaluation_entrypoint = f"{ev_module}.{evaluation['entrypoint']}"
                        break
                if evaluation_entrypoint != "Undefined":
                    break
            table.add_row(
                module,
                algo["name"],
                algo["entrypoint"],
                str(algo["decoupled"]),
                evaluation_entrypoint,
            )

    Console().print(table)
