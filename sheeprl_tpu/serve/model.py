"""Servable models: manifest-validated checkpoint loads and hot-swap sources.

:class:`GatewayModel` wraps the eval-builder registry's
:class:`~sheeprl_tpu.evals.service.EvalPolicy` (one batched jitted act per
family — the only algorithm-specific code the gateway ever touches) with the
two things serving adds: a **version** stamped on every response and a
**stable per-row state contract** (``init_state_rows``) for the batcher's
server-side recurrent state.

Two load paths, same builder:

- :func:`load_gateway_model` — cold start from a checkpoint path or a
  ``registry:best:<algo>:<env id>`` ref. The run's persisted config supplies
  the architecture; ``fabric.load`` verifies the manifest's per-array
  checksums (a torn or tampered checkpoint refuses to serve); the version is
  the manifest's training step.
- :class:`PolicySwapper` — live updates from a
  :class:`~sheeprl_tpu.plane.publish.PolicyPoller` channel (the same
  publication directory the in-run evaluator reads). A watcher thread polls
  for new versions, rebuilds the policy via the same builder, and swaps it
  into the batcher. A torn publication loads as None and is skipped — the
  gateway keeps serving what it has (inherited from the ckpt layer's
  torn-write resilience, never re-implemented here).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = ["GatewayModel", "PolicySwapper", "load_gateway_model"]


class GatewayModel:
    """One servable policy: ``act`` + ``init_state_rows`` + ``version``."""

    def __init__(
        self,
        policy,
        version: int,
        algo: str,
        env_id: str,
        checkpoint: Optional[str] = None,
    ):
        self.policy = policy
        self.version = int(version)
        self.algo = str(algo)
        self.env_id = str(env_id)
        self.checkpoint = checkpoint

    def act(self, obs, state, key):
        """The EvalPolicy contract: batched obs/state in, actions/state out."""
        return self.policy.act(obs, state, key)

    def init_state_rows(self, n: int):
        """Fresh recurrent state for ``n`` rows (None: stateless family)."""
        if self.policy.init_state is None:
            return None
        return self.policy.init_state(int(n))


def _forced_single_device_fabric(cfg):
    """The eval CLI's single-device fabric override (cli.evaluation /
    evals.service.evaluate_checkpoint): serving shares the eval stack's
    1-device placement and keeps the run's PRNG implementation."""
    from sheeprl_tpu.utils.utils import dotdict

    run_fabric = cfg.get("fabric", {}) or {}
    return dotdict(
        {
            "_target_": "sheeprl_tpu.fabric.Fabric",
            "devices": 1,
            "num_nodes": 1,
            "strategy": "auto",
            "accelerator": "auto",
            "precision": run_fabric.get("precision", "32-true"),
            "prng_impl": run_fabric.get("prng_impl", "rbg"),
            "callbacks": [],
        }
    )


def _builder_for(cfg) -> Callable:
    from sheeprl_tpu.evals.service import find_eval_builder, registered_eval_builders

    builder = find_eval_builder(cfg.algo.name)
    if builder is None:
        raise RuntimeError(
            f"No eval-policy builder registered for '{cfg.algo.name}'. "
            f"Registered: {registered_eval_builders()}"
        )
    return builder


def load_gateway_model(
    checkpoint_ref: str, registry_dir: str = "logs/registry"
) -> "tuple[GatewayModel, Any, Any, Any]":
    """Build a servable model from a checkpoint path or registry ref.

    Returns ``(model, cfg, observation_space, action_space)`` — the extras
    are what a swap source needs to rebuild policies against the same
    architecture and spaces (one probe env per gateway, not per swap).
    """
    import sheeprl_tpu
    from sheeprl_tpu.cli import _load_run_config
    from sheeprl_tpu.config.instantiate import instantiate
    from sheeprl_tpu.evals.registry import resolve_checkpoint_ref
    from sheeprl_tpu.evals.service import _policy_version_of, _probe_spaces

    sheeprl_tpu.register_algorithms()
    checkpoint_path, _record = resolve_checkpoint_ref(checkpoint_ref, registry_dir)
    cfg, _log_dir = _load_run_config(checkpoint_path)
    cfg.env.capture_video = False
    cfg.fabric = _forced_single_device_fabric(cfg)
    fabric = instantiate(cfg.fabric)
    state = fabric.load(checkpoint_path)  # manifest-validated (per-array checksums)
    builder = _builder_for(cfg)
    observation_space, action_space = _probe_spaces(cfg)
    policy = builder(fabric, cfg, state, observation_space, action_space)
    version = _policy_version_of(checkpoint_path) or 0
    model = GatewayModel(
        policy,
        version=version,
        algo=str(cfg.algo.name),
        env_id=str(cfg.env.id),
        checkpoint=os.path.abspath(checkpoint_path),
    )
    return model, cfg, observation_space, action_space


class PolicySwapper:
    """Watcher thread: new published policy versions → in-place swaps.

    Polls a :class:`~sheeprl_tpu.plane.publish.PolicyPoller` channel for
    versions newer than the serving model's, rebuilds the frozen policy with
    the family's eval builder (``builder(None, cfg, published_state, ...)``
    — the in-run evaluator's exact rebuild path), and calls ``swap_fn(new_
    model)``. Rebuild + swap run entirely off the dispatch path; the batcher
    picks the new reference up at its next batch.
    """

    def __init__(
        self,
        policy_root: str,
        cfg,
        observation_space,
        action_space,
        swap_fn: Callable[[GatewayModel], Any],
        base_model: GatewayModel,
        poll_interval_s: float = 0.2,
    ):
        from sheeprl_tpu.plane.publish import PolicyPoller

        self._poller = PolicyPoller(str(policy_root), poll_interval_s=poll_interval_s)
        self._cfg = cfg
        self._obs_space = observation_space
        self._act_space = action_space
        self._swap_fn = swap_fn
        self._builder = _builder_for(cfg)
        self._algo = base_model.algo
        self._env_id = base_model.env_id
        self._last_version = int(base_model.version)
        self._stop = threading.Event()
        self.swaps = 0
        self._thread = threading.Thread(
            target=self._run, name="serve-policy-swapper", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        interval = self._poller.poll_interval_s
        while not self._stop.wait(interval):
            self.poll_once()

    def poll_once(self) -> bool:
        """One poll step (also the test hook): swap if a newer valid version
        is published. Returns True on swap."""
        try:
            latest = self._poller.latest_version()
            if latest is None or latest <= self._last_version:
                return False
            state = self._poller.load(latest)
            if state is None:  # torn publication: keep serving what we have
                return False
            policy = self._builder(
                None, self._cfg, state, self._obs_space, self._act_space
            )
        except Exception:
            return False  # a bad publication must never take serving down
        model = GatewayModel(
            policy, version=latest, algo=self._algo, env_id=self._env_id
        )
        self._swap_fn(model)
        self._last_version = int(latest)
        self.swaps += 1
        return True

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)
