"""Shared-memory act-request transport: client processes ↔ gateway server.

The PR-7 trajectory plane moves whole rollout slabs learner-ward through
preallocated shared memory with tiny queue records (plane/slabs.py); this
ring is the same idea pointed the other way and sized for *serving*: each
client owns exactly one slot of a preallocated observation slab and one slot
of an action slab, so a request is

  client: write obs row into its slot → enqueue ``(slot, seq, reset)``
  server: batch whatever is queued → write action rows back into the same
          slots → post ``(seq, version)`` on that client's response queue

No observation or action ever crosses a pickling queue — only the tiny
commit records do. One-slot-per-client is the credit protocol collapsed to
its serving form: a client has at most one request in flight (it owns its
slot), so there is no free-list to manage and a crashed client can never
corrupt another client's rows.

The ring is ``spawn``-picklable like the trajectory slabs: cached numpy
views are dropped in ``__getstate__`` and rebuilt lazily on the other side.
``close()`` sets the shared stop event — blocked clients raise
:class:`~sheeprl_tpu.plane.slabs.PlaneClosed` instead of hanging.

Slab layout v2 adds a per-slot **metadata block** (three float64s: the
client's act()-entry stamp, its enqueue stamp, and a trace id — 0 when the
request is unsampled) so the request-path tracer can reconstruct
``client_enqueue``/``ring_transit`` spans for requests that crossed a
process boundary. The layout is **versioned**: ``__setstate__`` refuses to
unpickle a ring whose layout tag differs from this build's, so a stale peer
gets one clear error instead of silently misreading slab bytes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.obs.reqtrace import now as _now

__all__ = ["ActSlabRing", "RING_LAYOUT_VERSION"]

#: bump on ANY slab/queue-record layout change (fields, dtypes, ordering)
RING_LAYOUT_VERSION = 2


def _nbytes(shape: Tuple[int, ...], dtype: np.dtype) -> int:
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


class ActSlabRing:
    """Preallocated obs/action slabs with one slot per client."""

    def __init__(
        self,
        obs_spec: Dict[str, Tuple[Tuple[int, ...], Any]],
        act_shape: Tuple[int, ...],
        act_dtype: Any,
        n_clients: int,
        ctx=None,
    ):
        if int(n_clients) < 1:
            raise ValueError(f"n_clients must be >= 1, got {n_clients}")
        if ctx is None:
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
        self.n_clients = int(n_clients)
        self.obs_spec = {
            str(k): (tuple(shape), np.dtype(dtype)) for k, (shape, dtype) in obs_spec.items()
        }
        self.act_shape = tuple(act_shape)
        self.act_dtype = np.dtype(act_dtype)
        self._obs_blocks = {
            k: ctx.RawArray("b", self.n_clients * _nbytes(shape, dtype))
            for k, (shape, dtype) in self.obs_spec.items()
        }
        self._act_block = ctx.RawArray(
            "b", self.n_clients * _nbytes(self.act_shape, self.act_dtype)
        )
        # layout v2: per-slot (t_start, t_enqueue, trace_id) request metadata
        self._meta_block = ctx.RawArray("d", self.n_clients * 3)
        self._layout = RING_LAYOUT_VERSION
        #: deterministic client-side sampling: trace every k-th request per
        #: slot (0 = tracing off); set by the gateway from serve settings
        self.trace_every = 0
        self._requests = ctx.Queue()
        self._responses = [ctx.Queue() for _ in range(self.n_clients)]
        self._stop = ctx.Event()
        self._views: Optional[Dict[str, np.ndarray]] = None
        self._act_view: Optional[np.ndarray] = None
        self._meta_view: Optional[np.ndarray] = None

    @classmethod
    def from_example(
        cls, obs_row: Dict[str, np.ndarray], act_row: np.ndarray, n_clients: int, ctx=None
    ) -> "ActSlabRing":
        """Size the slabs from one example request/response row."""
        spec = {
            k: (tuple(np.asarray(v).shape), np.asarray(v).dtype)
            for k, v in obs_row.items()
        }
        act = np.asarray(act_row)
        return cls(spec, act.shape, act.dtype, n_clients, ctx=ctx)

    # ------------------------------------------------------------------ views

    def _obs_views(self) -> Dict[str, np.ndarray]:
        if self._views is None:
            self._views = {
                k: np.frombuffer(self._obs_blocks[k], dtype=dtype).reshape(
                    (self.n_clients,) + shape
                )
                for k, (shape, dtype) in self.obs_spec.items()
            }
        return self._views

    def _act_views(self) -> np.ndarray:
        if self._act_view is None:
            self._act_view = np.frombuffer(self._act_block, dtype=self.act_dtype).reshape(
                (self.n_clients,) + self.act_shape
            )
        return self._act_view

    def _meta_views(self) -> np.ndarray:
        if self._meta_view is None:
            self._meta_view = np.frombuffer(self._meta_block, dtype=np.float64).reshape(
                (self.n_clients, 3)
            )
        return self._meta_view

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_views"] = None  # numpy views don't cross process boundaries;
        state["_act_view"] = None  # rebuilt lazily from the RawArrays
        state["_meta_view"] = None
        return state

    def __setstate__(self, state):
        got = state.get("_layout")
        if got != RING_LAYOUT_VERSION:
            raise RuntimeError(
                f"ActSlabRing slab-layout mismatch: the pickled ring speaks "
                f"layout {got!r}, this build speaks {RING_LAYOUT_VERSION}. "
                f"Client and gateway must run the same sheeprl_tpu build — "
                f"refusing to attach rather than misread slab bytes."
            )
        self.__dict__.update(state)

    # ------------------------------------------------------------ client side

    def request(
        self,
        slot: int,
        obs_row: Dict[str, np.ndarray],
        seq: int,
        reset: bool,
        trace=None,
    ) -> None:
        """Write the obs row (and the request metadata) into this client's
        slot and commit the request. ``trace`` is an optional
        :class:`~sheeprl_tpu.obs.reqtrace.RequestTrace` baton; its stamps ride
        the slot-metadata block so the gateway can emit the client-side spans
        (CLOCK_MONOTONIC is system-wide — the stamps compare directly)."""
        views = self._obs_views()
        for k, (shape, dtype) in self.obs_spec.items():
            views[k][slot] = np.asarray(obs_row[k], dtype=dtype).reshape(shape)
        meta = self._meta_views()
        if trace is not None:
            trace.t_enqueue = _now()
            meta[slot] = (trace.t_start, trace.t_enqueue, float(trace.trace_id))
        else:
            meta[slot] = (0.0, 0.0, 0.0)
        self._requests.put((int(slot), int(seq), bool(reset)))

    def wait_response(self, slot: int, seq: int, timeout: float = 30.0) -> Tuple[np.ndarray, int]:
        """Block for this client's response; returns ``(action_row, version)``.

        Responses with a stale ``seq`` (from a request this client abandoned)
        are discarded. Raises PlaneClosed when the ring stops mid-wait.
        """
        from sheeprl_tpu.plane.slabs import PlaneClosed

        deadline = _now() + float(timeout)
        q = self._responses[int(slot)]
        while True:
            remaining = deadline - _now()
            if remaining <= 0:
                raise TimeoutError(f"serve ring response timed out (slot {slot})")
            try:
                got_seq, version, error = q.get(timeout=min(remaining, 0.1))
            except Exception:
                if self._stop.is_set():
                    raise PlaneClosed("serve ring closed while waiting for a response")
                continue
            if got_seq != int(seq):
                continue  # stale response from an abandoned request
            if error is not None:
                raise RuntimeError(f"serve request failed on the gateway: {error}")
            return self._act_views()[int(slot)].copy(), int(version)

    # ------------------------------------------------------------ server side

    def next_requests(self, timeout: float = 0.05) -> List[Tuple[int, int, bool]]:
        """Drain queued requests: block up to ``timeout`` for the first, then
        take everything immediately available (the coalescing window proper
        lives in the batcher — this just empties the wire)."""
        import queue as _queue

        out: List[Tuple[int, int, bool]] = []
        try:
            out.append(self._requests.get(timeout=timeout))
        except _queue.Empty:
            return out
        while True:
            try:
                out.append(self._requests.get_nowait())
            except _queue.Empty:
                return out

    def read_meta(self, slot: int):
        """The slot's request metadata, or None when the request was not
        sampled: a :class:`~sheeprl_tpu.obs.reqtrace.RequestTrace` rebuilt
        from the client's stamps."""
        t_start, t_enqueue, trace_id = self._meta_views()[int(slot)]
        if trace_id <= 0:
            return None
        from sheeprl_tpu.obs.reqtrace import RequestTrace

        return RequestTrace(int(trace_id), float(t_start), float(t_enqueue))

    def read_obs_row(self, slot: int) -> Dict[str, np.ndarray]:
        """Copy one client's observation row out of the slab (the batcher
        holds requests across the dispatch window; the client may not rewrite
        its slot until it gets a response, but copies keep that invariant
        local to the transport)."""
        views = self._obs_views()
        return {k: views[k][int(slot)].copy() for k in self.obs_spec}

    def respond(
        self, slot: int, seq: int, action_row: Optional[np.ndarray], version: int,
        error: Optional[str] = None,
    ) -> None:
        if action_row is not None:
            self._act_views()[int(slot)] = np.asarray(
                action_row, dtype=self.act_dtype
            ).reshape(self.act_shape)
        self._responses[int(slot)].put((int(seq), int(version), error))

    # -------------------------------------------------------------- lifecycle

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def close(self) -> None:
        self._stop.set()
        # cancel queue feeder threads so interpreter shutdown never blocks on
        # unflushed queue buffers (same discipline as plane/slabs.py)
        for q in [self._requests, *self._responses]:
            try:
                q.cancel_join_thread()
            except (AttributeError, OSError):
                pass
