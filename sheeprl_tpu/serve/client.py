"""The serve client API — the only sanctioned way to get actions out of a
gateway.

Clients never load checkpoints, never build agents, never see params: they
hand an observation row to the gateway and get ``(action_row, version)``
back (``tools/lint_serve.py`` enforces exactly that — a file using the
serve client API may not also reach for checkpoint loads or agent builds).

Two transports, one contract:

- :class:`LocalServeClient` — in-process (threads): submits straight into
  the gateway's :class:`~sheeprl_tpu.serve.batcher.RequestBatcher`. What the
  tests and the 1k-thread load harness drive.
- :class:`RingServeClient` — cross-process over an
  :class:`~sheeprl_tpu.serve.rings.ActSlabRing` slot (shared-memory slabs,
  tiny commit queues). Picklable into a spawned client process.

``act(obs_row, reset=False)`` returns ``(action_row, version)``; ``version``
is the model version that actually produced the action — under a hot-swap
it moves monotonically, and a client comparing versions across calls can
see the swap happen mid-episode. ``reset=True`` marks an episode boundary
(the gateway re-initializes that client's server-side recurrent state).
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.obs import reqtrace
from sheeprl_tpu.obs.reqtrace import RequestTrace
from sheeprl_tpu.obs.reqtrace import now as _now

__all__ = ["LocalServeClient", "RingServeClient"]

_client_counter = itertools.count()
_counter_lock = threading.Lock()


def _auto_id(prefix: str) -> str:
    with _counter_lock:
        return f"{prefix}{next(_client_counter)}"


class LocalServeClient:
    """In-process client: one logical actor, one recurrent-state key."""

    def __init__(self, batcher, client_id: Optional[str] = None):
        self._batcher = batcher
        self.client_id = str(client_id) if client_id is not None else _auto_id("local")
        self._pending = None
        self._closed = False

    def act(
        self,
        obs_row: Dict[str, np.ndarray],
        reset: bool = False,
        timeout: Optional[float] = 30.0,
    ) -> Tuple[np.ndarray, int]:
        """One request → one action row plus the serving model version."""
        if self._closed:
            raise RuntimeError(f"client {self.client_id} is closed")
        # one global read when tracing is off; a sampled request carries its
        # trace baton through the batcher and is emitted gateway-side
        trace = reqtrace.sample()
        if trace is not None:
            trace.t_enqueue = _now()
        pending = self._batcher.submit(self.client_id, obs_row, reset=reset, trace=trace)
        self._pending = pending
        try:
            return self._batcher.wait(pending, timeout=timeout)
        except TimeoutError:
            self._batcher.cancel(pending)
            raise
        finally:
            self._pending = None

    def close(self) -> None:
        """Disconnect: cancel anything in flight, drop server-side state."""
        self._closed = True
        pending = self._pending
        if pending is not None:
            self._batcher.cancel(pending)
        self._batcher.forget_client(self.client_id)


class RingServeClient:
    """Cross-process client bound to one :class:`ActSlabRing` slot.

    Construct in the parent with ``(ring, slot)`` and ship it to the child
    (the ring is spawn-picklable); or construct in the child from the ring
    it received. At most one request in flight — the client owns its slot.
    """

    def __init__(self, ring, slot: int):
        self._ring = ring
        self.slot = int(slot)
        self.client_id = f"ring{self.slot}"
        self._seq = 0

    def act(
        self,
        obs_row: Dict[str, np.ndarray],
        reset: bool = False,
        timeout: float = 30.0,
    ) -> Tuple[np.ndarray, int]:
        self._seq += 1
        # deterministic per-slot sampling (the child process has no tracer
        # installed — the ring carries the sampling knob and the stamps, the
        # gateway's tracer does the emitting)
        every = int(getattr(self._ring, "trace_every", 0) or 0)
        trace = None
        if every > 0 and (self._seq - 1) % every == 0:
            trace = RequestTrace((self.slot + 1) * 1_000_000 + self._seq, t_start=_now())
        self._ring.request(self.slot, obs_row, self._seq, reset, trace=trace)
        return self._ring.wait_response(self.slot, self._seq, timeout=timeout)

    def close(self) -> None:
        """Nothing to release: the slot is owned for the ring's lifetime and
        an unread response is discarded by the next act()'s seq check."""
