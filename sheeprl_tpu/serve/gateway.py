"""The policy-serving gateway: model + batcher + transports + lifecycle.

:class:`ServeGateway` owns one :class:`~sheeprl_tpu.serve.model.GatewayModel`
(loaded through the eval-builder registry from a checkpoint path or a
``registry:best:<algo>:<env id>`` ref), one
:class:`~sheeprl_tpu.serve.batcher.RequestBatcher` (fill-or-deadline request
coalescing), and optionally

- a :class:`~sheeprl_tpu.serve.model.PolicySwapper` watching a policy
  publication channel for in-place hot-swaps (``watch``), and
- an :class:`~sheeprl_tpu.serve.rings.ActSlabRing` server thread for
  cross-process clients (``start_ring``).

``drain()`` is the SIGTERM contract: stop accepting, finish every in-flight
request, stop the threads — asserted in ``tests/test_serve``.

:func:`rescore_through_gateway` is the gateway-path parity check: it runs
the eval service's exact frozen-greedy protocol (same pool, same seed
ladder, same per-step key schedule) but routes every episode row through
its own serve client, so the batcher coalesces each pool step into one
dispatch. Matched seeds ⇒ bitwise the returns
:func:`~sheeprl_tpu.evals.service.evaluate_checkpoint` produces — the
evidence that the serving path adds transport, not math
(``tools/bench_serve.py --matrix-parity`` commits it).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.obs.reqtrace import now as _now
from sheeprl_tpu.obs.reqtrace import unix_now as _unix_now
from sheeprl_tpu.utils.utils import dotdict

__all__ = [
    "ServeContext",
    "ServeGateway",
    "rescore_through_gateway",
    "run_serve_entrypoint",
    "serve_settings",
]

#: shipped defaults for the ``serve`` config group (configs/serve/default.yaml)
_SERVE_DEFAULTS: Dict[str, Any] = {
    "max_batch": 64,
    "deadline_ms": 10.0,
    "seed": 42,
    "max_clients": 1024,
    "registry_dir": "logs/registry",
    "poll_root": None,  # policy publication dir to watch for hot-swaps
    "poll_interval_s": 0.2,
    "drain_timeout_s": 30.0,
    "duration_s": 0.0,  # 0 → serve until signaled
    # ---- request-path observability (all off by default: ops stays None
    # ---- and the request path is byte-identical to the pre-ops gateway)
    "trace_sample_rate": 0.0,  # fraction of requests emitting span chains
    "access_log_sample_rate": 0.0,  # fraction of requests logged to access.jsonl
    "obs_dir": None,  # where traces/alerts/access/serve_live.json land
    "metrics_port": None,  # /metrics endpoint port (0 → ephemeral)
    "inject_dispatch_delay_s": 0.0,  # fault injection: stall device_dispatch
    "slo": {  # burn-rate objectives (obs/slo.py fills the rest)
        "enabled": False,
        "objectives": {
            "act_latency_p99_ms": 250.0,
            "availability": 0.999,
            "swap_staleness_s": 30.0,
        },
    },
}


def serve_settings(cfg) -> dotdict:
    """The ``serve`` knobs with shipped defaults filled in."""
    merged = dict(_SERVE_DEFAULTS)
    try:
        user = cfg.get("serve", {}) or {}
    except AttributeError:
        user = {}
    for key, value in dict(user).items():
        merged[key] = value
    return dotdict(merged)


class ServeGateway:
    """One serving endpoint: coalesced batched inference over one model."""

    def __init__(
        self,
        model,
        cfg=None,
        observation_space=None,
        action_space=None,
        max_batch: int = 64,
        deadline_s: float = 0.010,
        seed: int = 42,
    ):
        from sheeprl_tpu.serve.batcher import RequestBatcher

        self.cfg = cfg
        self.observation_space = observation_space
        self.action_space = action_space
        self.batcher = RequestBatcher(
            model, max_batch=max_batch, deadline_s=deadline_s, seed=seed
        )
        self._swapper = None
        self._ring = None
        self._ring_stop = threading.Event()
        self._ring_thread: Optional[threading.Thread] = None
        self.ops = None

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_ref: str,
        registry_dir: str = "logs/registry",
        max_batch: int = 64,
        deadline_s: float = 0.010,
        seed: int = 42,
    ) -> "ServeGateway":
        """Cold start: manifest-validated load via the eval-builder registry."""
        from sheeprl_tpu.serve.model import load_gateway_model

        model, cfg, obs_space, act_space = load_gateway_model(
            checkpoint_ref, registry_dir=registry_dir
        )
        return cls(
            model,
            cfg=cfg,
            observation_space=obs_space,
            action_space=act_space,
            max_batch=max_batch,
            deadline_s=deadline_s,
            seed=seed,
        )

    # ------------------------------------------------------------- client API

    def client(self, client_id: Optional[str] = None):
        """An in-process serve client (the sanctioned access path)."""
        from sheeprl_tpu.serve.client import LocalServeClient

        return LocalServeClient(self.batcher, client_id=client_id)

    # ------------------------------------------------------------ ops surface

    def enable_ops(self, settings: Dict[str, Any], out_dir: Optional[str] = None):
        """Attach the request-path observability planes (tracing, SLO engine,
        access log, ``/metrics``) per the ``serve.*`` knobs. Returns the
        :class:`~sheeprl_tpu.serve.ops.ServeOps` — or None when every knob is
        off, in which case the request path is untouched."""
        from sheeprl_tpu.serve.ops import ServeOps

        if self.ops is not None:
            raise RuntimeError("gateway ops surface is already enabled")
        out = out_dir or settings.get("obs_dir") or "logs/serve_obs"
        self.ops = ServeOps.build(
            settings,
            str(out),
            status_fn=self.status,
            staleness_fn=self._swap_staleness,
        )
        if self.ops is not None:
            self.batcher.attach_ops(self.ops)
            if self._ring is not None and self.ops.tracer is not None:
                self._ring.trace_every = int(self.ops.tracer._every)
        return self.ops

    def _swap_staleness(self) -> float:
        """Seconds the serving model has lagged the newest published policy:
        0 when no swapper is attached or serving is current; otherwise the
        age of the newest unpicked-up publication."""
        swapper = self._swapper
        if swapper is None:
            return 0.0
        try:
            latest = swapper._poller.latest_version()
            if latest is None or int(latest) <= int(swapper._last_version):
                return 0.0
            from sheeprl_tpu.plane.publish import policy_path

            mtime = os.path.getmtime(policy_path(swapper._poller.root, int(latest)))
            return max(0.0, _unix_now() - mtime)
        except Exception:
            return 0.0

    # --------------------------------------------------------------- hot-swap

    def watch(self, policy_root: str, poll_interval_s: float = 0.2):
        """Start hot-swapping from a policy publication channel."""
        from sheeprl_tpu.serve.model import PolicySwapper

        if self._swapper is not None:
            raise RuntimeError("gateway is already watching a policy channel")
        self._swapper = PolicySwapper(
            policy_root,
            self.cfg,
            self.observation_space,
            self.action_space,
            swap_fn=self.batcher.swap,
            base_model=self.batcher.model,
            poll_interval_s=poll_interval_s,
        )
        return self._swapper

    # ----------------------------------------------------------- ring serving

    def start_ring(self, n_clients: int, ctx=None):
        """Create the shared-memory ring for ``n_clients`` external clients
        and start the server thread pumping it into the batcher."""
        from sheeprl_tpu.serve.rings import ActSlabRing

        if self._ring is not None:
            raise RuntimeError("gateway already serves a ring")
        if self.observation_space is None or self.action_space is None:
            raise RuntimeError("ring serving needs the gateway's env spaces")
        obs_row = {
            k: np.asarray(space.sample())
            for k, space in self.observation_space.spaces.items()
        }
        act_row = np.asarray(self.action_space.sample())
        self._ring = ActSlabRing.from_example(obs_row, act_row, n_clients, ctx=ctx)
        if self.ops is not None and self.ops.tracer is not None:
            # the ring carries the sampling knob: child-process clients have
            # no tracer installed, they stamp every trace_every-th request
            self._ring.trace_every = int(self.ops.tracer._every)
        self._ring_thread = threading.Thread(
            target=self._serve_ring, name="serve-ring", daemon=True
        )
        self._ring_thread.start()
        return self._ring

    def _serve_ring(self) -> None:
        from sheeprl_tpu.serve.batcher import ServeClosed

        ring = self._ring
        while not self._ring_stop.is_set():
            requests = ring.next_requests(timeout=0.05)
            if not requests:
                continue
            tickets = []
            for slot, seq, reset in requests:
                obs = ring.read_obs_row(slot)
                trace = ring.read_meta(slot)
                try:
                    ticket = self.batcher.submit(f"ring{slot}", obs, reset=reset, trace=trace)
                except ServeClosed as exc:
                    ring.respond(slot, seq, None, -1, error=str(exc))
                    continue
                tickets.append((slot, seq, ticket))
            # the tickets resolve together (one coalesced dispatch covers
            # them); waiting here costs nothing extra and keeps the pump
            # single-threaded
            for slot, seq, ticket in tickets:
                try:
                    action, version = self.batcher.wait(ticket, timeout=60.0)
                except Exception as exc:
                    ring.respond(slot, seq, None, -1, error=str(exc))
                    continue
                ring.respond(slot, seq, action, version)

    # -------------------------------------------------------------- lifecycle

    def status(self) -> Dict[str, Any]:
        model = self.batcher.model
        status = {
            "algo": model.algo,
            "env": model.env_id,
            "model_version": int(model.version),
            "checkpoint": model.checkpoint,
            "swapper": self._swapper is not None,
            **self.batcher.stats(),
        }
        ops = self.ops
        if ops is not None:
            if ops.tracer is not None:
                status["trace"] = {
                    "sample_rate": float(ops.tracer.sample_rate),
                    "sampled_requests": int(ops.tracer.sampled),
                }
            if ops.slo is not None:
                status["slo"] = ops.slo.status()
        return status

    def drain(self, timeout: float = 30.0) -> bool:
        """SIGTERM path: finish in-flight requests, then stop everything."""
        drained = self.batcher.drain(timeout=timeout)
        self._shutdown_aux()
        return drained

    def close(self) -> None:
        self.batcher.close()
        self._shutdown_aux()

    def _shutdown_aux(self) -> None:
        if self.ops is not None:
            self.ops.close()
            self.ops = None
        if self._swapper is not None:
            self._swapper.close()
            self._swapper = None
        self._ring_stop.set()
        if self._ring is not None:
            self._ring.close()
        if self._ring_thread is not None:
            self._ring_thread.join(timeout=10.0)
            self._ring_thread = None


class ServeContext:
    """Spawn-picklable bundle for running a serve client in a child process
    (the :class:`~sheeprl_tpu.plane.worker.PlayerContext` shape, collapsed to
    the client side): the ring, the client's slot, and a ``module:function``
    entry point called as ``entry(client, spec)``. ``child_main`` pins the
    child to the CPU jax backend before any jax import — serve clients never
    touch the device."""

    def __init__(self, ring, slot: int, entry: str, spec: Optional[Dict[str, Any]] = None):
        self.ring = ring
        self.slot = int(slot)
        self.entry = str(entry)
        self.spec = dict(spec or {})


def child_main(ctx: ServeContext) -> None:
    """Client-process entry point (spawned, never forked)."""
    os.environ["JAX_PLATFORMS"] = "cpu"  # before ANY jax import
    import importlib

    from sheeprl_tpu.serve.client import RingServeClient

    module_name, _, fn_name = ctx.entry.partition(":")
    fn = getattr(importlib.import_module(module_name), fn_name)
    client = RingServeClient(ctx.ring, ctx.slot)
    fn(client, ctx.spec)


# ---------------------------------------------------------------------------
# gateway-path parity rescore
# ---------------------------------------------------------------------------


def rescore_through_gateway(
    checkpoint_ref: str,
    episodes: int = 10,
    seed0: int = 1000,
    registry_dir: str = "logs/registry",
    max_steps: int = 0,
) -> Dict[str, Any]:
    """Frozen-greedy protocol with every episode row behind a serve client.

    Same pool, same seed ladder, same per-dispatch key schedule as
    :func:`~sheeprl_tpu.evals.service.run_parallel_episodes` — one full
    coalesced batch per pool step — so matched seeds reproduce the eval
    service's returns bitwise. Returns the eval-shaped result dict plus the
    gateway's ``versions_served`` / occupancy stats.
    """
    from sheeprl_tpu.evals.service import eval_settings, iqm, make_eval_pool

    n = int(episodes)
    gateway = ServeGateway.from_checkpoint(
        checkpoint_ref,
        registry_dir=registry_dir,
        max_batch=n,  # every pool step coalesces into exactly one dispatch
        deadline_s=5.0,
        seed=int(seed0),  # the runner's PRNGKey(seed0) act-key schedule
    )
    try:
        cfg = gateway.cfg
        settings = eval_settings(cfg)
        max_steps = int(max_steps or settings.max_steps or 0)
        pool, seeds = make_eval_pool(cfg, None, n, int(seed0), prefix="serve")
        try:
            single_space = getattr(pool, "single_action_space", None)
            act_shape = tuple(single_space.shape) if single_space is not None else ()
            clients = [gateway.client(f"episode{i}") for i in range(n)]
            obs, _ = pool.reset(seed=[int(s) for s in seeds])
            returns = np.zeros(n, dtype=np.float64)
            lengths = np.zeros(n, dtype=np.int64)
            alive = np.ones(n, dtype=bool)
            need_reset = np.zeros(n, dtype=bool)
            steps = 0
            while alive.any():
                tickets = [
                    clients[i]._batcher.submit(
                        clients[i].client_id,
                        {k: np.asarray(v[i]) for k, v in obs.items()},
                        reset=bool(need_reset[i]),
                    )
                    for i in range(n)
                ]
                rows = [gateway.batcher.wait(t, timeout=60.0) for t in tickets]
                actions = np.stack([np.asarray(a) for a, _v in rows])
                real_actions = actions.reshape((n,) + act_shape)
                obs, rewards, terminated, truncated, _ = pool.step(real_actions)
                done = np.logical_or(
                    np.asarray(terminated).reshape(n), np.asarray(truncated).reshape(n)
                )
                rewards = np.asarray(rewards, dtype=np.float64).reshape(n)
                returns += rewards * alive
                lengths += alive.astype(np.int64)
                alive &= ~done
                # autoreset re-enters finished rows next step: fresh recurrent
                # state then, exactly the runner's reset_fn(state, ~done)
                need_reset = done.copy()
                steps += 1
                if max_steps and steps >= max_steps:
                    break
        finally:
            pool.close()
        stats = gateway.batcher.stats()
        return {
            "protocol": "frozen-greedy/gateway",
            "checkpoint": gateway.batcher.model.checkpoint,
            "algo": gateway.batcher.model.algo,
            "env": gateway.batcher.model.env_id,
            "n": n,
            "seed0": int(seed0),
            "seeds": [int(s) for s in seeds],
            "returns": [float(r) for r in returns],
            "lengths": [int(l) for l in lengths],
            "mean": float(np.mean(returns)),
            "std": float(np.std(returns)),
            "iqm": iqm(returns),
            "versions_served": stats["versions_served"],
            "batches": stats["batches"],
            "mean_batch_occupancy": stats["mean_batch_occupancy"],
            "failed_requests": stats["failed_requests"],
        }
    finally:
        gateway.close()


# ---------------------------------------------------------------------------
# CLI entrypoint body
# ---------------------------------------------------------------------------


def run_serve_entrypoint(serve_cfg) -> None:
    """The ``sheeprl_tpu.cli.serve`` body: build the gateway, serve the ring,
    hot-swap when a channel is configured, drain cleanly on SIGTERM."""
    import signal

    settings = serve_settings(serve_cfg)
    gateway = ServeGateway.from_checkpoint(
        serve_cfg.checkpoint_path,
        registry_dir=str(settings.registry_dir),
        max_batch=int(settings.max_batch),
        deadline_s=float(settings.deadline_ms) / 1e3,
        seed=int(settings.seed),
    )
    if settings.poll_root:
        gateway.watch(str(settings.poll_root), poll_interval_s=float(settings.poll_interval_s))
    ops = gateway.enable_ops(settings)
    gateway.start_ring(int(settings.max_clients))
    status = gateway.status()
    print(
        f"[serve] gateway up: {status['algo']} on {status['env']} "
        f"v{status['model_version']} (max_batch={settings.max_batch}, "
        f"deadline={settings.deadline_ms}ms, max_clients={settings.max_clients})",
        flush=True,
    )
    if ops is not None:
        port = ops.prom.port if ops.prom is not None else None
        print(
            f"[serve] ops surface on: dir={ops.out_dir} "
            f"trace_rate={settings.trace_sample_rate} "
            f"slo={'on' if ops.slo is not None else 'off'} "
            f"metrics_port={port}",
            flush=True,
        )

    stop = threading.Event()

    def _on_term(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    deadline = _now() + float(settings.duration_s) if settings.duration_s else None
    while not stop.is_set():
        if deadline is not None and _now() >= deadline:
            break
        stop.wait(timeout=5.0)
        s = gateway.status()
        print(
            f"[serve] v{s['model_version']} requests={s['requests']} "
            f"batches={s['batches']} occupancy={s['mean_batch_occupancy']} "
            f"p95={s['act_latency'].get('p95_ms')}ms swaps={s['swaps']} "
            f"failed={s['failed_requests']}",
            flush=True,
        )
    drained = gateway.drain(timeout=float(settings.drain_timeout_s))
    print(f"[serve] drained={'clean' if drained else 'TIMEOUT'}; gateway down", flush=True)
