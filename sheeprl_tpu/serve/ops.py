"""The gateway ops surface: tracing, SLO engine, access log, ``/metrics``.

One :class:`ServeOps` per gateway composes the request-path observability
planes the tentacles of ``obs/`` already provide:

- a :class:`~sheeprl_tpu.obs.reqtrace.ServeTracer` (two Chrome-trace lanes,
  client + gateway pids, ``trace_serve_*.jsonl`` — picked up by
  ``tools/trace_view.py`` alongside the learner's trace),
- a :class:`~sheeprl_tpu.obs.slo.SloEngine` evaluated on its own daemon
  tick, fed per-request by the batcher and per-tick by the gateway's
  swap-staleness probe; alert firings land in ``alerts.jsonl`` and trip the
  flight recorder (``reason=slo_burn``),
- a sampled JSONL **access log** (``access.jsonl``: one line per k-th
  retired request),
- a :class:`~sheeprl_tpu.obs.live.PromServer` over a serve-only
  :class:`~sheeprl_tpu.obs.live.LiveExporter` (``interval_s=0`` — a scrape
  recomputes the snapshot at most once a second), exporting the per-version
  request/latency breakdown, per-stage percentiles, batch occupancy, and
  SLO burn rates on ``/metrics``; the same snapshot is written to
  ``serve_live.json`` at drain for ``tools/serve_report.py``.

Everything here is opt-in per knob (``configs/serve/default.yaml``):
:meth:`ServeOps.build` returns None when no knob is on, and the batcher's
``ops is None`` fast path keeps the off-state request path byte-identical
to the pre-observability gateway (asserted in tests/test_serve).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, Optional

from sheeprl_tpu.obs.reqtrace import now as _now
from sheeprl_tpu.obs.reqtrace import unix_now as _unix_now

__all__ = ["AccessLog", "ServeOps"]


class AccessLog:
    """Sampled JSONL request log: every k-th retired request, one line."""

    def __init__(self, path: str, sample_rate: float):
        rate = max(0.0, min(float(sample_rate), 1.0))
        self._every = 1 if rate >= 1.0 else (max(1, round(1.0 / rate)) if rate > 0 else 0)
        self._lock = threading.Lock()
        self._seen = 0
        self.written = 0
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self._file = open(path, "a")

    def maybe_log(self, record: Dict[str, Any]) -> None:
        if self._every <= 0:
            return
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self._every:
                return
            if self._file.closed:
                return
            self._file.write(json.dumps(record) + "\n")
            self.written += 1
            if self.written % 64 == 0:
                self._file.flush()

    def close(self) -> None:
        with self._lock:
            if not self._file.closed:
                self._file.flush()
                self._file.close()


class ServeOps:
    """Per-gateway composition of the request-path observability planes."""

    def __init__(
        self,
        settings: Dict[str, Any],
        out_dir: str,
        status_fn: Callable[[], Dict[str, Any]],
        staleness_fn: Optional[Callable[[], float]] = None,
    ):
        from sheeprl_tpu.obs.slo import SloEngine, slo_settings

        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self._status_fn = status_fn
        self._staleness_fn = staleness_fn
        self.inject_dispatch_delay_s = float(
            settings.get("inject_dispatch_delay_s") or 0.0
        )
        # flight recorder: ride the run's (telemetry active) or own a
        # standalone one so a bare gateway still dumps flight_slo_burn_*.json
        self.flight = None
        self._own_flight = False
        try:
            from sheeprl_tpu.obs.telemetry import get_telemetry

            tel = get_telemetry()
            if tel is not None and tel.flight is not None:
                self.flight = tel.flight
        except Exception:
            pass
        if self.flight is None:
            from sheeprl_tpu.obs.live import FlightRecorder

            self.flight = FlightRecorder(
                capacity=2048, min_interval_s=5.0, max_dumps=8, out_dir=self.out_dir
            )
            self._own_flight = True
        # tracing
        self.tracer = None
        rate = float(settings.get("trace_sample_rate") or 0.0)
        if rate > 0:
            from sheeprl_tpu.obs import reqtrace

            self.tracer = reqtrace.ServeTracer(self.out_dir, rate, flight_ring=self.flight)
            reqtrace.install(self.tracer)
        # access log
        self.access = None
        access_rate = float(settings.get("access_log_sample_rate") or 0.0)
        if access_rate > 0:
            self.access = AccessLog(os.path.join(self.out_dir, "access.jsonl"), access_rate)
        # SLO engine + its evaluation tick
        self.slo = None
        self._slo_stop = threading.Event()
        self._slo_thread = None
        slo_cfg = slo_settings(settings.get("slo"))
        if bool(slo_cfg.get("enabled")):
            self.slo = SloEngine(
                slo_cfg,
                alerts_path=os.path.join(self.out_dir, "alerts.jsonl"),
                on_alert=self._on_alert,
                clock=_now,
            )
            self._slo_thread = threading.Thread(
                target=self._slo_loop, name="serve-slo", daemon=True
            )
            self._slo_thread.start()
        # live snapshot + optional /metrics endpoint
        from sheeprl_tpu.obs.live import LiveExporter

        self.exporter = LiveExporter(
            self.snapshot,
            path=os.path.join(self.out_dir, "serve_live.json"),
            interval_s=0.0,  # serve-only mode: scrapes recompute, <= 1/s
        )
        self.prom = None
        metrics_port = settings.get("metrics_port")
        if metrics_port is not None:
            from sheeprl_tpu.obs.live import PromServer

            self.prom = PromServer(self.exporter, port=int(metrics_port))
            self.prom.start()

    @classmethod
    def build(
        cls,
        settings: Dict[str, Any],
        out_dir: str,
        status_fn: Callable[[], Dict[str, Any]],
        staleness_fn: Optional[Callable[[], float]] = None,
    ) -> Optional["ServeOps"]:
        """A :class:`ServeOps` when any ops knob is on, else None (the
        zero-cost off state — the batcher never sees a sink)."""
        slo_cfg = dict(settings.get("slo") or {})
        enabled = (
            float(settings.get("trace_sample_rate") or 0.0) > 0
            or float(settings.get("access_log_sample_rate") or 0.0) > 0
            or float(settings.get("inject_dispatch_delay_s") or 0.0) > 0
            or bool(slo_cfg.get("enabled"))
            or settings.get("metrics_port") is not None
        )
        if not enabled:
            return None
        return cls(settings, out_dir, status_fn, staleness_fn=staleness_fn)

    # -- request-path feed (called by the batcher's dispatcher thread) -------

    def on_request(
        self,
        client_id: str,
        latency_s: Optional[float],
        version: int,
        ok: bool = True,
        trace=None,
        stamps=None,
        rows: int = 0,
    ) -> None:
        """One retired ticket: feed the SLO engine, the access log, and —
        for a sampled request — emit its six-stage span chain."""
        if self.slo is not None:
            self.slo.record_request(latency_s, failed=not ok)
        tracer = self.tracer
        if tracer is not None and trace is not None and ok and stamps is not None:
            t_submit, t_collect, t_model, t_done, t_end = stamps
            tracer.emit_request(
                trace,
                t_submit,
                t_collect,
                t_model,
                t_done,
                t_end,
                client_id=client_id,
                version=version,
            )
        if self.access is not None:
            self.access.maybe_log(
                {
                    "ts_unix": round(_unix_now(), 6),
                    "client": str(client_id),
                    "latency_ms": round(latency_s * 1e3, 3) if latency_s is not None else None,
                    "version": int(version),
                    "ok": bool(ok),
                    "trace_id": int(trace.trace_id) if trace is not None else 0,
                    "batch_rows": int(rows),
                }
            )

    def on_cancelled(self, n: int) -> None:
        if self.slo is not None:
            for _ in range(int(n)):
                self.slo.record_request(None, cancelled=True)

    # -- SLO tick ------------------------------------------------------------

    def _slo_loop(self) -> None:
        interval = float(self.slo.settings.get("eval_interval_s") or 1.0)
        while not self._slo_stop.wait(interval):
            self.slo_tick()

    def slo_tick(self) -> None:
        """One evaluation tick (also the test hook): sample the staleness
        gauge, then update every burn-rate alert pair."""
        if self.slo is None:
            return
        try:
            if self._staleness_fn is not None:
                self.slo.record_staleness(float(self._staleness_fn()))
            self.slo.evaluate()
        except Exception:
            pass  # observability must never take the gateway down

    def _on_alert(self, rec: Dict[str, Any]) -> None:
        from sheeprl_tpu.obs.counters import add_slo_alert

        add_slo_alert(1)
        if self.flight is not None:
            try:
                self.flight.trigger("slo_burn", rec)
            except Exception:
                pass

    # -- the ops snapshot (PromServer /metrics + serve_live.json) ------------

    def snapshot(self) -> Dict[str, Any]:
        """Gateway status adapted to the live-exporter shape: flat scalars,
        per-stage percentiles under ``phase_percentiles`` (so they export as
        ``phase_duration_ms{phase="serve/..."}``), the per-version breakdown
        under ``serve_versions``, and the SLO engine under ``slo``."""
        status = dict(self._status_fn() or {})
        snap: Dict[str, Any] = {
            k: v for k, v in status.items() if isinstance(v, (int, float, bool))
        }
        phase: Dict[str, Any] = {}
        lat = status.get("act_latency")
        if isinstance(lat, dict):
            phase["serve/act_latency"] = lat
        for name, pct in (status.get("stage_latency") or {}).items():
            phase[f"serve/{name}"] = pct
        snap["phase_percentiles"] = phase
        occ = status.get("batch_occupancy") or {}
        for key in ("p50", "p95", "p99", "max"):
            if occ.get(key) is not None:
                snap[f"batch_occupancy_{key}"] = occ[key]
        snap["serve_versions"] = status.get("versions") or {}
        if self.tracer is not None:
            snap["trace_sampled_requests"] = self.tracer.sampled
        if self.access is not None:
            snap["access_log_lines"] = self.access.written
        if self.slo is not None:
            snap["slo"] = self.slo.status()
        snap["ts_unix"] = round(_unix_now(), 3)
        return snap

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Drain-time teardown: final SLO tick, final snapshot to disk, stop
        the metrics server, flush every sink."""
        self._slo_stop.set()
        if self._slo_thread is not None:
            self._slo_thread.join(timeout=10.0)
        self.slo_tick()  # final evaluation so late burns still alert
        try:
            self.exporter.write_once()
        except Exception:
            pass
        if self.prom is not None:
            try:
                self.prom.stop()
            except Exception:
                pass
        if self.tracer is not None:
            from sheeprl_tpu.obs import reqtrace

            if reqtrace.installed() is self.tracer:
                reqtrace.install(None)
            self.tracer.close()
        if self.access is not None:
            self.access.close()
        if self.slo is not None:
            self.slo.close()
