"""Policy-serving gateway (howto/serving.md).

Batched-inference serving over the evaluation stack: checkpoints (or
``registry:best:<algo>:<env id>`` refs) load through the eval-builder
registry into a :class:`~sheeprl_tpu.serve.model.GatewayModel`; concurrent
client ``act(obs)`` requests coalesce into one device dispatch per batch
window (:class:`~sheeprl_tpu.serve.batcher.RequestBatcher`, fill-or-
deadline); models hot-swap in place when a policy publication channel moves
(:class:`~sheeprl_tpu.serve.model.PolicySwapper`); clients ride threads
(:class:`~sheeprl_tpu.serve.client.LocalServeClient`) or processes over
shared-memory slabs (:class:`~sheeprl_tpu.serve.rings.ActSlabRing`,
:class:`~sheeprl_tpu.serve.client.RingServeClient`).

Client code touches ONLY the client classes and :class:`ServeGateway` —
never checkpoint loads or agent builders (``tools/lint_serve.py``).
"""

from sheeprl_tpu.serve.batcher import RequestBatcher, ServeClosed, ServeRequestError
from sheeprl_tpu.serve.client import LocalServeClient, RingServeClient
from sheeprl_tpu.serve.gateway import (
    ServeContext,
    ServeGateway,
    rescore_through_gateway,
    run_serve_entrypoint,
    serve_settings,
)
from sheeprl_tpu.serve.model import GatewayModel, PolicySwapper, load_gateway_model
from sheeprl_tpu.serve.rings import ActSlabRing

__all__ = [
    "ActSlabRing",
    "GatewayModel",
    "LocalServeClient",
    "PolicySwapper",
    "RequestBatcher",
    "RingServeClient",
    "ServeClosed",
    "ServeContext",
    "ServeGateway",
    "ServeRequestError",
    "load_gateway_model",
    "rescore_through_gateway",
    "run_serve_entrypoint",
    "serve_settings",
]
