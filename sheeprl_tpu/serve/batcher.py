"""Request coalescing: N concurrent ``act(obs)`` requests → one device dispatch.

The gateway's clients each hold *one observation row*; the model wants a
batch. :class:`RequestBatcher` sits between them: a dispatcher thread
collects pending requests and launches **one** ``policy.act`` per batch
window — when the batch fills (``max_batch`` rows) or the oldest pending
request's latency deadline (``deadline_s``) expires, whichever comes first.
That is the SEED-RL inference-server shape (PAPERS.md: Espeholt et al.
2019): inference cost amortizes across clients instead of paying one device
program per caller.

Determinism contract (the gateway-path parity check rides on it): rows are
stacked in submission order, and the PRNG stream is ``key, act_key =
jax.random.split(key)`` once per dispatch from ``PRNGKey(seed)`` — exactly
the key schedule of :func:`sheeprl_tpu.evals.service.run_parallel_episodes`.
A driver that routes every episode row of an eval pool through its own
client (one full batch per pool step) therefore reproduces the eval
service's returns bitwise at matched seeds.

Recurrent families: the batcher keeps each client's recurrent state
server-side (keyed by ``client_id``), concatenates the rows for a dispatch,
and splits the new state back afterwards — clients stay stateless wire
protocols. ``reset=True`` on a request replaces that client's state with a
fresh ``init_state`` row before the dispatch (episode boundary).

Hot-swap contract: :meth:`swap` atomically replaces the model reference
*between* dispatches. A batch in flight finishes on the params it started
with; the next batch rides the new ones; every response carries the version
of the model that actually produced it (the monotone version telemetry the
load harness asserts on). Recurrent client states survive a swap — the
gateway only swaps within one run's publication channel, where carrying
state across a params update is the actor-learner plane's normal mode.

Failure isolation: a cancelled request (client disconnected mid-wait) is
dropped at dispatch time without wedging the batch; a dispatch error fails
only the requests in that batch (each waiter gets the exception), the
dispatcher survives. ``drain()`` is the SIGTERM path: stop accepting,
finish everything queued, then stop the thread.
"""

from __future__ import annotations

import threading
import time  # sleep only — clock reads go through obs.reqtrace (lint_telemetry)
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.obs.reqtrace import now as _now

__all__ = ["RequestBatcher", "ServeClosed", "ServeRequestError"]

#: the four gateway-side stages of a request's life (the client-side two,
#: ``client_enqueue``/``ring_transit``, are stamped by the client and only
#: *emitted* here); per-stage StreamingHists ride stats() -> status()
STAGE_NAMES = ("queue_wait", "batch_assembly", "device_dispatch", "respond")


class ServeClosed(RuntimeError):
    """The gateway is draining/closed and accepts no new requests."""


class ServeRequestError(RuntimeError):
    """A request failed (its batch's dispatch raised, or it was abandoned)."""


class _Pending:
    """One queued request: a ticket the client waits on."""

    __slots__ = (
        "client_id",
        "obs",
        "reset",
        "t_submit",
        "event",
        "action",
        "version",
        "error",
        "cancelled",
        "trace",
    )

    def __init__(self, client_id: str, obs: Dict[str, np.ndarray], reset: bool, trace=None):
        self.client_id = client_id
        self.obs = obs
        self.reset = bool(reset)
        self.t_submit = _now()
        self.event = threading.Event()
        self.action: Optional[np.ndarray] = None
        self.version: Optional[int] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False
        #: optional RequestTrace baton (obs/reqtrace) — None when unsampled
        self.trace = trace


def _stack_rows(rows: List[Any]):
    """Stack per-client pytree rows along a new leading batch axis."""
    import jax

    return jax.tree.map(lambda *leaves: np.stack([np.asarray(l) for l in leaves]), *rows)


def _concat_state_rows(rows: List[Any]):
    """Concatenate per-client state slices (leading axis 1 each) to a batch."""
    import jax

    return jax.tree.map(
        lambda *leaves: np.concatenate([np.asarray(l) for l in leaves], axis=0), *rows
    )


def _split_state_rows(state: Any, n: int) -> List[Any]:
    """Split a batched state back into n single-row slices (leading axis)."""
    import jax

    return [jax.tree.map(lambda leaf: np.asarray(leaf)[i : i + 1], state) for i in range(n)]


class RequestBatcher:
    """Fill-or-deadline request coalescer around one servable model.

    ``model`` must expose ``act(obs, state, key) -> (actions, new_state)``
    (the :class:`~sheeprl_tpu.evals.service.EvalPolicy` contract, batched on
    axis 0), ``init_state_rows(n)`` (fresh recurrent state for n rows, or
    None for stateless families), and ``version`` (int, stamped on every
    response) — :class:`sheeprl_tpu.serve.model.GatewayModel`.
    """

    def __init__(
        self,
        model,
        max_batch: int = 64,
        deadline_s: float = 0.010,
        seed: int = 42,
    ):
        if int(max_batch) < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.deadline_s = max(float(deadline_s), 0.0)
        self._model = model
        self._seed = int(seed)
        self._key = None  # lazily PRNGKey(seed): no jax import before first use
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_Pending] = []
        self._states: Dict[str, Any] = {}
        self._draining = False
        self._stopped = False
        # standalone stats (live without telemetry installed — the load
        # harness and the tests read these; the obs counters mirror them)
        from sheeprl_tpu.obs.hist import StreamingHist

        self._latency = StreamingHist()
        # per-stage decomposition + batch occupancy + per-version breakdown
        # (always-on: a handful of clock reads and hist records per batch —
        # the ops surface reads these through gateway.status())
        self._stage_hists = {name: StreamingHist() for name in STAGE_NAMES}
        self._occupancy = StreamingHist()
        self._per_version: Dict[int, Dict[str, Any]] = {}
        #: optional ServeOps sink (serve/ops.py): tracing, access log, SLO
        #: feed, fault injection — None keeps the request path pre-PR-19
        self._ops = None
        self._stats_lock = threading.Lock()
        self._requests = 0
        self._batches = 0
        self._batch_rows = 0
        self._deadline_misses = 0
        self._failed = 0
        self._swaps = 0
        self._versions_served: List[int] = []
        self._thread = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- client API

    def submit(
        self, client_id: str, obs: Dict[str, np.ndarray], reset: bool = False, trace=None
    ) -> _Pending:
        """Queue one observation row; returns the ticket to :meth:`wait` on.
        ``trace`` is the request's sampled trace baton (or None)."""
        pending = _Pending(str(client_id), obs, reset, trace=trace)
        with self._cv:
            if self._draining or self._stopped:
                raise ServeClosed("gateway is draining: no new requests accepted")
            self._queue.append(pending)
            self._cv.notify_all()
        from sheeprl_tpu.obs.counters import add_serve_requests

        add_serve_requests(1)
        with self._stats_lock:
            self._requests += 1
        return pending

    def wait(self, pending: _Pending, timeout: Optional[float] = None):
        """Block until the ticket's batch dispatched; returns
        ``(action_row, version)`` or raises :class:`ServeRequestError`."""
        if not pending.event.wait(timeout):
            raise TimeoutError("serve request timed out waiting for its batch")
        if pending.error is not None:
            raise ServeRequestError(str(pending.error)) from pending.error
        return pending.action, pending.version

    def cancel(self, pending: _Pending) -> None:
        """Client disconnect: the request is dropped at dispatch time; a
        response already in flight is simply never read. Never wedges the
        batch the request rode in."""
        pending.cancelled = True
        with self._cv:
            self._cv.notify_all()

    def forget_client(self, client_id: str) -> None:
        """Drop a disconnected client's server-side recurrent state."""
        with self._cv:
            self._states.pop(str(client_id), None)

    # ------------------------------------------------------------ gateway API

    @property
    def model(self):
        return self._model

    def attach_ops(self, ops) -> None:
        """Install (or with ``None`` remove) the request-path observability
        sink — a :class:`sheeprl_tpu.serve.ops.ServeOps`. Atomic reference
        assignment; the dispatcher reads it once per batch."""
        self._ops = ops

    def swap(self, model) -> int:
        """Atomically install ``model`` for all *subsequent* dispatches;
        in-flight batches finish on the old reference. Returns the new
        version."""
        from sheeprl_tpu.obs.counters import add_serve_swap

        self._model = model  # atomic reference assignment
        add_serve_swap(1)
        with self._stats_lock:
            self._swaps += 1
        return int(model.version)

    def drain(self, timeout: float = 30.0) -> bool:
        """SIGTERM path: refuse new requests, finish every queued one, stop
        the dispatcher. Returns True when the queue fully drained in time."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        deadline = _now() + float(timeout)
        while _now() < deadline:
            with self._cv:
                if not self._queue:
                    break
            time.sleep(0.005)
        self.close()
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
        for p in leftovers:  # only on timeout: fail loud, never hang clients
            p.error = ServeClosed("gateway stopped before this request dispatched")
            p.event.set()
        if leftovers:
            from sheeprl_tpu.obs.counters import add_serve_failed

            add_serve_failed(len(leftovers))
            with self._stats_lock:
                self._failed += len(leftovers)
            ops = self._ops
            if ops is not None:
                for p in leftovers:
                    ops.on_request(p.client_id, None, 0, ok=False)
        return not leftovers

    def close(self) -> None:
        with self._cv:
            self._draining = True
            self._stopped = True
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=30.0)

    def stats(self) -> Dict[str, Any]:
        """Snapshot for the load harness / live status."""
        occ = self._occupancy
        occupancy_dist = {
            "count": occ.n,
            "p50": round(occ.quantile(0.5) or 0.0, 3),
            "p95": round(occ.quantile(0.95) or 0.0, 3),
            "p99": round(occ.quantile(0.99) or 0.0, 3),
            "max": round(occ.max, 3),
        }
        stage_latency = {
            name: hist.percentiles() for name, hist in self._stage_hists.items()
        }
        with self._stats_lock:
            batches = self._batches
            occupancy = (self._batch_rows / batches) if batches else 0.0
            versions = {
                str(v): {"requests": rec["requests"], **rec["latency"].percentiles()}
                for v, rec in sorted(self._per_version.items())
            }
            return {
                "requests": self._requests,
                "batches": batches,
                "mean_batch_occupancy": round(occupancy, 3),
                "deadline_misses": self._deadline_misses,
                "failed_requests": self._failed,
                "swaps": self._swaps,
                "versions_served": list(self._versions_served),
                "act_latency": self._latency.percentiles(),
                "stage_latency": stage_latency,
                "batch_occupancy": occupancy_dist,
                "versions": versions,
            }

    # ------------------------------------------------------------- dispatcher

    def _collect(self) -> List[_Pending]:
        """Block until a batch is ready: full, or deadline-expired non-empty,
        or stopping. Returns [] only when stopped with an empty queue."""
        with self._cv:
            while True:
                while not self._queue:
                    if self._stopped or (self._draining and not self._queue):
                        return []
                    self._cv.wait(timeout=0.05)
                t_first = self._queue[0].t_submit
                if self._draining:
                    # finish queued work as fast as possible: no deadline wait
                    batch = self._queue[: self.max_batch]
                    del self._queue[: len(batch)]
                    return batch
                remaining = self.deadline_s - (_now() - t_first)
                if len(self._queue) >= self.max_batch or remaining <= 0:
                    batch = self._queue[: self.max_batch]
                    del self._queue[: len(batch)]
                    return batch
                self._cv.wait(timeout=remaining)

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                return
            self._dispatch(batch)

    def _batch_states(self, batch: List[_Pending], model) -> Optional[Any]:
        """Per-client recurrent state rows for this batch, fresh where the
        client is new or asked for a reset; None for stateless families."""
        rows = []
        stateless = True
        for p in batch:
            state = None if p.reset else self._states.get(p.client_id)
            if state is None:
                fresh = model.init_state_rows(1)
                if fresh is None:
                    rows.append(None)
                    continue
                state = fresh
            stateless = False
            rows.append(state)
        if stateless:
            return None
        return _concat_state_rows(rows)

    def _dispatch(self, batch: List[_Pending]) -> None:
        import jax

        from sheeprl_tpu.obs import hist as _obs_hist
        from sheeprl_tpu.obs.counters import add_serve_batch, add_serve_failed

        ops = self._ops  # one atomic read per batch, same as the model
        t_collect = _now()
        # a miss is the dispatcher launching late (previous batch still on the
        # device), not a deadline-expired partial fill — that one is by design
        lateness = t_collect - (batch[0].t_submit + self.deadline_s)
        deadline_miss = self.deadline_s > 0 and lateness > 0.5 * self.deadline_s
        live = [p for p in batch if not p.cancelled]
        if ops is not None and len(live) < len(batch):
            ops.on_cancelled(len(batch) - len(live))
        if not live:
            return
        model = self._model  # one atomic read: the whole batch rides one model
        try:
            obs = _stack_rows([p.obs for p in live])
            state = self._batch_states(live, model)
            if self._key is None:
                self._key = jax.random.PRNGKey(self._seed)
            self._key, act_key = jax.random.split(self._key)
            t_model = _now()
            if ops is not None and ops.inject_dispatch_delay_s > 0:
                # fault injection (serve.inject_dispatch_delay_s): a slow
                # device, charged to the device_dispatch stage — the SLO
                # e2e test trips the fast-burn alert with this
                time.sleep(ops.inject_dispatch_delay_s)
            actions, new_state = model.act(obs, state, act_key)
            actions = np.asarray(actions)
        except BaseException as exc:  # fail this batch's waiters, survive
            for p in live:
                p.error = exc
                p.event.set()
            add_serve_failed(len(live))
            with self._stats_lock:
                self._failed += len(live)
            if ops is not None:
                for p in live:
                    ops.on_request(p.client_id, None, int(model.version), ok=False)
            return
        t_done = _now()
        if new_state is not None:
            with self._cv:
                for p, row in zip(live, _split_state_rows(new_state, len(live))):
                    self._states[p.client_id] = row
        version = int(model.version)
        for i, p in enumerate(live):
            p.action = actions[i]
            p.version = version
            p.event.set()
        t_end = _now()
        # stage decomposition: every live request experienced this batch's
        # assembly/dispatch/respond windows plus its own queue wait, so the
        # per-request stage sums reconstruct the end-to-end act latency
        assembly_s = t_model - t_collect
        dispatch_s = t_done - t_model
        respond_s = t_end - t_done
        stage = self._stage_hists
        with self._stats_lock:
            ver_rec = self._per_version.get(version)
            if ver_rec is None:
                from sheeprl_tpu.obs.hist import StreamingHist

                ver_rec = self._per_version[version] = {
                    "requests": 0,
                    "latency": StreamingHist(),
                }
            ver_rec["requests"] += len(live)
        for p in live:
            latency = t_end - p.t_submit
            self._latency.record(latency)
            ver_rec["latency"].record(latency)
            _obs_hist.observe("Time/serve_act_latency", latency)
            stage["queue_wait"].record(t_collect - p.t_submit)
            stage["batch_assembly"].record(assembly_s)
            stage["device_dispatch"].record(dispatch_s)
            stage["respond"].record(respond_s)
            if ops is not None:
                ops.on_request(
                    p.client_id,
                    latency,
                    version,
                    ok=True,
                    trace=p.trace,
                    stamps=(p.t_submit, t_collect, t_model, t_done, t_end),
                    rows=len(live),
                )
        self._occupancy.record(len(live))
        add_serve_batch(len(live), deadline_miss=deadline_miss)
        with self._stats_lock:
            self._batches += 1
            self._batch_rows += len(live)
            if deadline_miss:
                self._deadline_misses += 1
            if not self._versions_served or self._versions_served[-1] != version:
                self._versions_served.append(version)
        if deadline_miss:
            self._flag_deadline_miss(len(live), lateness)

    def _flag_deadline_miss(self, rows: int, lateness_s: float) -> None:
        """Arm the flight recorder on a late launch (telemetry runs only)."""
        try:
            from sheeprl_tpu.obs.telemetry import get_telemetry

            tel = get_telemetry()
            if tel is not None and tel.flight is not None:
                tel.flight.trigger(
                    "serve_deadline_miss",
                    {
                        "rows": int(rows),
                        "lateness_ms": round(lateness_s * 1e3, 3),
                        "deadline_ms": round(self.deadline_s * 1e3, 3),
                    },
                )
        except Exception:
            pass  # observability must never take the gateway down
