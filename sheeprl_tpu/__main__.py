"""``python -m sheeprl_tpu`` → the train CLI (reference ``sheeprl.py`` shim)."""

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    run()
