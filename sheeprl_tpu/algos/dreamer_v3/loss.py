"""DreamerV3 world-model loss (reference ``sheeprl/algos/dreamer_v3/loss.py``:
reconstruction_loss :11-115).

Pure-jnp: every term is built from raw decoder/head outputs inside the jitted
train step, so XLA fuses the whole Eq. 5 computation. Returns the scalar loss
plus a metrics dict (the reference returns an 8-tuple; a dict keeps the
aggregator wiring self-describing).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.distributions import (
    Bernoulli,
    Independent,
    OneHotCategorical,
    kl_divergence,
)

sg = jax.lax.stop_gradient


def categorical_kl(p_logits: jnp.ndarray, q_logits: jnp.ndarray) -> jnp.ndarray:
    """KL( Cat(p) ‖ Cat(q) ) summed over the stochastic dim.

    Logits ``[..., S, D]`` (already log-softmaxed by the unimix) → ``[...]``.
    """
    p = Independent(OneHotCategorical(logits=p_logits), 1)
    q = Independent(OneHotCategorical(logits=q_logits), 1)
    return kl_divergence(p, q)


def reconstruction_loss(
    po: Dict[str, Any],
    observations: Dict[str, jnp.ndarray],
    pr: Any,
    rewards: jnp.ndarray,
    priors_logits: jnp.ndarray,
    posteriors_logits: jnp.ndarray,
    kl_dynamic: float = 0.5,
    kl_representation: float = 0.1,
    kl_free_nats: float = 1.0,
    kl_regularizer: float = 1.0,
    pc: Optional[Any] = None,
    continue_targets: Optional[jnp.ndarray] = None,
    continue_scale_factor: float = 1.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Eq. 5 of the DV3 paper, matching reference loss.py:11-115 exactly:
    NLL of observations/rewards/continues + KL-balanced dynamic(0.5)/
    representation(0.1) losses with 1 free nat.

    ``priors_logits``/``posteriors_logits``: ``[T, B, S, D]``.
    Returns ``(scalar_loss, metrics)``.
    """
    observation_loss = -sum(po[k].log_prob(observations[k]) for k in po)
    reward_loss = -pr.log_prob(rewards)

    kl = categorical_kl(sg(posteriors_logits), priors_logits)
    dyn_loss = kl_dynamic * jnp.maximum(kl, kl_free_nats)
    repr_loss_raw = categorical_kl(posteriors_logits, sg(priors_logits))
    repr_loss = kl_representation * jnp.maximum(repr_loss_raw, kl_free_nats)
    kl_loss = dyn_loss + repr_loss

    continue_loss = jnp.zeros_like(reward_loss)
    if pc is not None and continue_targets is not None:
        continue_loss = continue_scale_factor * -pc.log_prob(continue_targets)

    total = jnp.mean(kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss)
    metrics = {
        "Loss/world_model_loss": total,
        "Loss/observation_loss": jnp.mean(observation_loss),
        "Loss/reward_loss": jnp.mean(reward_loss),
        "Loss/state_loss": jnp.mean(kl_loss),
        "Loss/continue_loss": jnp.mean(continue_loss),
        "State/kl": jnp.mean(kl),
        "User/DynLoss": jnp.mean(dyn_loss),
        "User/ReprLoss": jnp.mean(repr_loss),
        "State/post_entropy": jnp.mean(
            Independent(OneHotCategorical(logits=sg(posteriors_logits)), 1).entropy()
        ),
        "State/prior_entropy": jnp.mean(
            Independent(OneHotCategorical(logits=sg(priors_logits)), 1).entropy()
        ),
    }
    return total, metrics


def continue_distribution(logits: jnp.ndarray) -> Any:
    """Independent Bernoulli over the trailing dim (the continue head)."""
    return Independent(Bernoulli(logits=logits), 1)
