"""DreamerV3 utilities (reference ``sheeprl/algos/dreamer_v3/utils.py``).

- :data:`AGGREGATOR_KEYS` — the metric allow-list (reference :14-39).
- :func:`update_moments` — the return-normalizer percentile EMA
  (reference Moments :42-67) as a *functional* state update; the cross-rank
  ``all_gather`` becomes a ``lax.all_gather`` over the mesh axis when called
  inside the sharded train step.
- :func:`compute_lambda_values` — TD(λ) backward recursion (reference :70-81)
  as one reversed ``lax.scan``.
- :func:`test` — greedy rollout on a fresh env (reference :86-137).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.obs.dist import instrumented_all_gather as dist_all_gather

sg = jax.lax.stop_gradient

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Params/exploration_amount",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "User/LambdaValues",
    "User/Advantages",
    "User/Entropy",
    "User/PredictedRewards",
    "User/PredictedValues",
    "User/DynLoss",
    "User/ReprLoss",
}


def init_moments() -> Dict[str, jnp.ndarray]:
    return {"low": jnp.zeros(()), "high": jnp.zeros(())}


def update_moments(
    state: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    decay: float = 0.99,
    max_: float = 1e8,
    percentile_low: float = 0.05,
    percentile_high: float = 0.95,
    axis_name: Optional[str] = None,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """EMA of the 5%/95% percentiles of λ-returns (reference Moments :61-67).

    When ``axis_name`` is given (inside shard_map) the percentiles are taken
    over the values gathered from the whole mesh, matching the reference's
    ``fabric.all_gather``. Returns ``(new_state, offset, invscale)``, all
    stop-gradiented.
    """
    x = sg(x)
    if axis_name is not None:
        x = dist_all_gather(x, axis_name)
    low = jnp.quantile(x, percentile_low)
    high = jnp.quantile(x, percentile_high)
    new_low = decay * state["low"] + (1 - decay) * low
    new_high = decay * state["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / max_, new_high - new_low)
    return {"low": new_low, "high": new_high}, sg(new_low), sg(invscale)


def compute_lambda_values(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    continues: jnp.ndarray,
    lmbda: float = 0.95,
) -> jnp.ndarray:
    """TD(λ) returns over ``[H, ...]`` (reference :70-81): one reversed scan,
    ``continues`` already folded with γ by the caller."""
    interm = rewards + continues * values * (1 - lmbda)

    def step(nxt, inp):
        interm_t, cont_t = inp
        val = interm_t + cont_t * lmbda * nxt
        return val, val

    _, vals = jax.lax.scan(step, values[-1], (interm, continues), reverse=True)
    return vals


def prepare_obs(
    obs: Dict[str, np.ndarray], cnn_keys, mlp_keys, n_envs: int
) -> Dict[str, np.ndarray]:
    """Host-side obs dict → float arrays shaped for the models: cnn keys keep
    uint8 [C,H,W] folded over frame-stack and are normalized on device; mlp
    keys flattened to [n_envs, -1]."""
    out = {}
    for k in cnn_keys:
        v = np.asarray(obs[k])
        out[k] = v.reshape(n_envs, -1, *v.shape[-2:])
    for k in mlp_keys:
        v = np.asarray(obs[k])
        out[k] = v.reshape(n_envs, -1).astype(np.float32)
    return out


def normalize_obs_jnp(obs: Dict[str, jnp.ndarray], cnn_keys) -> Dict[str, jnp.ndarray]:
    """uint8 pixels → [0, 1] floats on device (reference /255 at
    dreamer_v3.py:619-624)."""
    return {
        k: (jnp.asarray(v, jnp.float32) / 255.0 if k in cnn_keys else jnp.asarray(v, jnp.float32))
        for k, v in obs.items()
    }


def test(
    player_fns: Dict[str, Any],
    params: Dict[str, Any],
    fabric,
    cfg,
    log_dir: str,
    test_name: str = "",
    sample_actions: bool = False,
    normalize_fn=None,
):
    """Greedy episode on a fresh single env (reference utils.py:86-137).

    ``normalize_fn(obs, cnn_keys)`` overrides the pixel normalization —
    DV3 uses /255 (default), DV2/DV1 pass their /255−0.5 variant.
    """
    import gymnasium as gym  # noqa: F401

    from sheeprl_tpu.envs.vector import make_eval_env

    if normalize_fn is None:
        normalize_fn = normalize_obs_jnp

    env = make_eval_env(
        cfg, log_dir, prefix="test" + (f"_{test_name}" if test_name else "")
    )
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    done = False
    cumulative_rew = 0.0
    key = jax.random.PRNGKey(cfg.seed)
    obs = env.reset(seed=cfg.seed)[0]
    state = player_fns["init_states"](params["world_model"], 1)
    act_fn = player_fns["exploration_action"] if sample_actions else player_fns["greedy_action"]
    while not done:
        prepared = prepare_obs(obs, cnn_keys, mlp_keys, 1)
        norm = normalize_fn(prepared, cnn_keys)
        key, k = jax.random.split(key)
        if sample_actions:
            actions, state = act_fn(
                params["world_model"], params["actor"], state, norm, k, jnp.float32(0.0)
            )
        else:
            actions, state = act_fn(params["world_model"], params["actor"], state, norm, k)
        if len(np.asarray(actions[0]).shape) > 1 and not isinstance(
            env.action_space, gym.spaces.Box
        ):
            real_actions = np.array([np.argmax(np.asarray(a), axis=-1) for a in actions])
        else:
            real_actions = np.concatenate([np.asarray(a) for a in actions], -1)
        obs, reward, done, truncated, _ = env.step(
            real_actions.reshape(env.action_space.shape)
        )
        done = done or truncated or cfg.dry_run
        cumulative_rew += float(reward)
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
