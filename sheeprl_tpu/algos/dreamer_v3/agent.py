"""DreamerV3 agent — flax modules, functional player, Hafner init.

Behavioral contract from the reference ``sheeprl/algos/dreamer_v3/agent.py``
(CNNEncoder :30, MLPEncoder :85, CNN/MLPDecoder :138-259, RecurrentModel :262,
RSSM :314-457, PlayerDV3 :460-585, Actor :588-767, build_models :900-1144).

TPU-native design (NOT a translation):

- The RSSM exposes *single-step* methods (``dynamic``, ``imagination``); the
  time loop lives in the train step as ``jax.lax.scan`` so XLA fuses the whole
  sequence into one program instead of T Python GRU steps
  (reference dreamer_v3.py:121-133 — SURVEY.md "hard parts" #1).
- The stateful ``PlayerDV3`` (mutates ``self.recurrent_state`` etc.,
  reference agent.py:516-537) becomes an explicit ``(actions, recurrent,
  stochastic)`` pytree threaded through pure jitted functions with
  ``jnp.where`` masking for per-env resets.
- Hafner initialization (reference utils.py init_weights/uniform_init_weights
  + build_models :1109-1119) is a pure transform over the freshly-initialized
  param pytree — truncated-normal for every kernel, uniform/zero overrides for
  the named output heads.
- Distributions are built *inside* jit from raw head outputs; sampling takes
  explicit PRNG keys.
"""

from __future__ import annotations

import math
import re
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.norm import FastLayerNorm

from sheeprl_tpu.distributions import (
    Bernoulli,
    Independent,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    TanhNormal,
    TruncatedNormal,
)
from sheeprl_tpu.models import MLP, CNN, DeCNN, LayerNormGRUCell, resolve_activation

sg = jax.lax.stop_gradient


# ---------------------------------------------------------------------------
# encoders / decoders
# ---------------------------------------------------------------------------


class CNNEncoder(nn.Module):
    """Image encoder (reference agent.py:30-82): ``stages`` conv blocks of
    k=4/s=2/p=1 with channels ``[1,2,4,...]×multiplier``, channel-last
    LayerNorm (free in NHWC), SiLU, then flatten. Input ``[..., C, H, W]``."""

    keys: Sequence[str]
    channels_multiplier: int
    stages: int = 4
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        x = CNN(
            channels=[(2**i) * self.channels_multiplier for i in range(self.stages)],
            kernel_sizes=4,
            strides=2,
            paddings=1,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            flatten=True,
            dtype=self.dtype,
        )(x)
        return x


class MLPEncoder(nn.Module):
    """Vector encoder (reference agent.py:85-135): symlog inputs, N dense
    blocks with LayerNorm+SiLU."""

    keys: Sequence[str]
    mlp_layers: int = 4
    dense_units: int = 512
    layer_norm: bool = True
    activation: Any = "silu"
    symlog_inputs: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            symlog_inputs=self.symlog_inputs,
            dtype=self.dtype,
        )(x)


class MultiEncoderDV3(nn.Module):
    """Concat of the cnn and mlp encoders' features (reference wraps both in a
    MultiEncoder; same semantics)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    channels_multiplier: int
    stages: int
    mlp_layers: int
    dense_units: int
    layer_norm: bool = True
    cnn_act: Any = "silu"
    dense_act: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        feats = []
        if self.cnn_keys:
            feats.append(
                CNNEncoder(
                    keys=self.cnn_keys,
                    channels_multiplier=self.channels_multiplier,
                    stages=self.stages,
                    layer_norm=self.layer_norm,
                    activation=self.cnn_act,
                    dtype=self.dtype,
                    name="cnn_encoder",
                )(obs)
            )
        if self.mlp_keys:
            feats.append(
                MLPEncoder(
                    keys=self.mlp_keys,
                    mlp_layers=self.mlp_layers,
                    dense_units=self.dense_units,
                    layer_norm=self.layer_norm,
                    activation=self.dense_act,
                    dtype=self.dtype,
                    name="mlp_encoder",
                )(obs)
            )
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    @staticmethod
    def output_width(
        cnn_keys: Sequence[str],
        mlp_keys: Sequence[str],
        image_size: Tuple[int, int],
        channels_multiplier: int,
        stages: int,
        dense_units: int,
    ) -> int:
        """Static feature width: CNN flatten (k=4/s=2/p=1 halves each stage,
        channels double from ``channels_multiplier``) + MLP ``dense_units``."""
        width = 0
        if cnn_keys:
            h, w = image_size[0] >> stages, image_size[1] >> stages
            width += h * w * channels_multiplier * 2 ** (stages - 1)
        if mlp_keys:
            width += dense_units
        return width


class CNNDecoder(nn.Module):
    """Pixel decoder (reference agent.py:138-211): Linear projection to the
    encoder's 4×4 feature map, then transposed-conv stages back to the image;
    output shifted by +0.5. The final conv keeps bias and gets the
    uniform-head init (name ``head``)."""

    output_channels: Sequence[int]
    channels_multiplier: int
    stages: int
    image_size: Tuple[int, int]
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> jnp.ndarray:
        total_c = sum(self.output_channels)
        top_c = (2 ** (self.stages - 1)) * self.channels_multiplier
        base = self.image_size[0] // (2**self.stages)
        x = nn.Dense(top_c * base * base, dtype=self.dtype)(latent)
        lead = x.shape[:-1]
        x = jnp.reshape(x, lead + (top_c, base, base))
        hidden = [
            (2**i) * self.channels_multiplier for i in reversed(range(self.stages - 1))
        ]
        if hidden:
            x = DeCNN(
                channels=hidden,
                kernel_sizes=4,
                strides=2,
                paddings=1,
                activation=self.activation,
                final_activation=self.activation,
                layer_norm=self.layer_norm,
                norm_eps=1e-3,
                bias=not self.layer_norm,
                dtype=self.dtype,
            )(x)
        x = DeCNN(
            channels=[total_c],
            kernel_sizes=4,
            strides=2,
            paddings=1,
            activation="identity",
            layer_norm=False,
            bias=True,
            dtype=self.dtype,
            name="head",
        )(x)
        # stay in the compute dtype: the conv output is already bf16-limited
        # under mixed precision, so a pixel-space +0.5 in bf16 costs at most
        # ~2^-9 (quarter-pixel) of extra rounding while halving the bytes of
        # the reconstruction tensor and its layout-normalization copy — the
        # MSE loss converts to f32 inside its reduce fusion
        return x + jnp.asarray(0.5, x.dtype)


class MLPDecoder(nn.Module):
    """Vector decoder (reference agent.py:214-259): shared dense trunk,
    one linear head per key (heads get the uniform init, names ``head_<k>``)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 512
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            dtype=self.dtype,
        )(latent)
        return {
            k: nn.Dense(dim, dtype=self.dtype, name=f"head_{k}")(x).astype(jnp.float32)
            for k, dim in zip(self.keys, self.output_dims)
        }


# ---------------------------------------------------------------------------
# recurrent model / RSSM
# ---------------------------------------------------------------------------


class RecurrentModel(nn.Module):
    """Dense pre-layer + LayerNorm GRU cell (reference agent.py:262-311)."""

    recurrent_state_size: int
    dense_units: int
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
        feat = MLP(
            hidden_sizes=[self.dense_units],
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            dtype=self.dtype,
        )(x)
        # the carried state stays f32 (the cell's gate mix promotes back)
        return LayerNormGRUCell(
            self.recurrent_state_size, bias=False, layer_norm=True, dtype=self.dtype, name="gru"
        )(feat, h).astype(jnp.float32)


class _StochasticModel(nn.Module):
    """MLP trunk + logits head — shared shape of the transition (prior) and
    representation (posterior) models. The head carries the uniform init."""

    hidden_size: int
    stoch_size: int  # stochastic_size * discrete_size
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = MLP(
            hidden_sizes=[self.hidden_size],
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            dtype=self.dtype,
        )(x)
        # categorical logits in f32: the unimix/log-softmax/KL math is
        # precision-sensitive
        return nn.Dense(self.stoch_size, dtype=self.dtype, name="head")(x).astype(jnp.float32)


class _RepresentationModel(nn.Module):
    """Posterior trunk with the embed half of the first layer split out.

    Mathematically identical to ``_StochasticModel`` over
    ``concat([h, embed])`` — the joint first-layer kernel is stored as ONE
    parameter (same init statistics as the reference's single Linear,
    reference agent.py:406-424) and sliced at apply time — but exposes
    ``project_embed`` so the train step can batch the embed projection over
    the whole ``[T, B]`` sequence *outside* the sequential RSSM scan: the
    embed width (e.g. 4096 from the CNN) dwarfs the recurrent width (512),
    so this removes ~8/9 of the posterior-trunk FLOPs and weight streaming
    from the latency-critical per-timestep path.
    """

    hidden_size: int
    stoch_size: int  # stochastic_size * discrete_size
    h_size: int
    embed_size: int
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    def setup(self):
        self.kernel = self.param(
            "trunk_kernel",
            nn.initializers.lecun_normal(),
            (self.h_size + self.embed_size, self.hidden_size),
        )
        if self.layer_norm:
            self.norm = FastLayerNorm(epsilon=1e-3, dtype=self.dtype, name="trunk_ln")
        else:
            self.bias = self.param(
                "trunk_bias", nn.initializers.zeros_init(), (self.hidden_size,)
            )
        self.head = nn.Dense(self.stoch_size, dtype=self.dtype, name="head")

    def _cast(self, x: jnp.ndarray) -> jnp.ndarray:
        return x.astype(self.dtype) if self.dtype is not None else x

    def project_embed(self, embed: jnp.ndarray) -> jnp.ndarray:
        return self._cast(embed) @ self._cast(self.kernel[self.h_size :])

    def from_projected(self, h: jnp.ndarray, embed_proj: jnp.ndarray) -> jnp.ndarray:
        x = self._cast(h) @ self._cast(self.kernel[: self.h_size]) + self._cast(embed_proj)
        if self.layer_norm:
            x = self.norm(x)
        else:
            x = x + self._cast(self.bias)
        x = resolve_activation(self.activation)(x)
        return self.head(x).astype(jnp.float32)

    def __call__(self, h: jnp.ndarray, embed: jnp.ndarray) -> jnp.ndarray:
        return self.from_projected(h, self.project_embed(embed))


def uniform_mix(logits: jnp.ndarray, discrete: int, unimix: float) -> jnp.ndarray:
    """1% uniform mixture on categorical logits (reference agent.py:392-404).

    ``logits`` is ``[..., S*D]`` flat; returns the same flat shape.
    """
    shape = logits.shape
    logits = jnp.reshape(logits, shape[:-1] + (-1, discrete))
    if unimix > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        probs = (1.0 - unimix) * probs + unimix / discrete
        logits = jnp.log(probs)
    return jnp.reshape(logits, shape)


def compute_stochastic_state(
    logits: jnp.ndarray,
    discrete: int,
    key: Optional[jax.Array],
    sample: bool = True,
    gumbel: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Sample (straight-through) or take the mode of the categorical latent
    (reference dreamer_v2/utils.py:39-58). ``logits`` flat ``[..., S*D]`` →
    state ``[..., S, D]``.

    ``gumbel`` (shape ``[..., S, D]``) is pre-drawn Gumbel(0,1) noise: the
    train scans generate it for the whole sequence in one vectorized draw
    outside the time loop, leaving only an add+argmax on the sequential path
    (``argmax(logits + g)`` is the same sampler ``jax.random.categorical``
    uses, and is invariant to the log-softmax normalization)."""
    shape = logits.shape
    logits = jnp.reshape(logits, shape[:-1] + (-1, discrete))
    if sample and gumbel is not None:
        one = jax.nn.one_hot(
            jnp.argmax(logits + gumbel, axis=-1), discrete, dtype=logits.dtype
        )
        probs = jax.nn.softmax(logits, axis=-1)
        return one + probs - jax.lax.stop_gradient(probs)
    dist = OneHotCategoricalStraightThrough(logits=logits)
    return dist.rsample(key) if sample else dist.mode


class RSSM(nn.Module):
    """Recurrent state-space model (reference agent.py:314-457).

    All methods are single-step over a batch; callers scan them over time.
    The stochastic state is carried *flat* ``[..., S*D]``.
    """

    recurrent_state_size: int
    stochastic_size: int
    discrete_size: int
    dense_units: int
    hidden_size: int
    embed_size: int
    representation_hidden_size: Optional[int] = None
    layer_norm: bool = True
    unimix: float = 0.01
    activation: Any = "silu"
    dtype: Optional[Any] = None

    def setup(self):
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            layer_norm=self.layer_norm,
            activation=self.activation,
            dtype=self.dtype,
        )
        stoch = self.stochastic_size * self.discrete_size
        self.representation_model = _RepresentationModel(
            hidden_size=self.representation_hidden_size or self.hidden_size,
            stoch_size=stoch,
            h_size=self.recurrent_state_size,
            embed_size=self.embed_size,
            layer_norm=self.layer_norm,
            activation=self.activation,
            dtype=self.dtype,
        )
        self.transition_model = _StochasticModel(
            hidden_size=self.hidden_size,
            stoch_size=stoch,
            layer_norm=self.layer_norm,
            activation=self.activation,
            dtype=self.dtype,
        )

    def _transition(
        self,
        recurrent_out: jnp.ndarray,
        key: Optional[jax.Array],
        sample_state: bool = True,
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Prior logits + (sampled|mode) prior, flat (reference :426-439)."""
        logits = uniform_mix(self.transition_model(recurrent_out), self.discrete_size, self.unimix)
        state = compute_stochastic_state(
            logits, self.discrete_size, key, sample=sample_state, gumbel=gumbel
        )
        return logits, jnp.reshape(state, state.shape[:-2] + (-1,))

    def _representation(
        self, recurrent_state: jnp.ndarray, embedded_obs: jnp.ndarray, key: jax.Array
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Posterior logits + sampled posterior, flat (reference :406-424)."""
        return self._representation_projected(
            recurrent_state, self.project_embed(embedded_obs), key
        )

    def project_embed(self, embedded_obs: jnp.ndarray) -> jnp.ndarray:
        """Batchable (non-sequential) half of the posterior trunk — hoist it
        out of the time scan and feed ``dynamic_projected``."""
        return self.representation_model.project_embed(embedded_obs)

    def _representation_projected(
        self,
        recurrent_state: jnp.ndarray,
        embed_proj: jnp.ndarray,
        key: Optional[jax.Array],
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        logits = uniform_mix(
            self.representation_model.from_projected(recurrent_state, embed_proj),
            self.discrete_size,
            self.unimix,
        )
        state = compute_stochastic_state(logits, self.discrete_size, key, gumbel=gumbel)
        return logits, jnp.reshape(state, state.shape[:-2] + (-1,))

    def dynamic(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        is_first: jnp.ndarray,
        key: jax.Array,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One posterior step with is_first resets (reference :352-404).

        All inputs are ``[B, ...]``; ``posterior`` flat ``[B, S*D]``. Returns
        ``(recurrent_state, posterior, posterior_logits, prior_logits)``.
        """
        return self.dynamic_projected(
            posterior, recurrent_state, action, self.project_embed(embedded_obs), is_first, key
        )

    def dynamic_projected(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embed_proj: jnp.ndarray,
        is_first: jnp.ndarray,
        key: jax.Array,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """``dynamic`` with the embed projection precomputed (the train scan
        hoists ``project_embed`` over [T, B] outside the time loop)."""
        init_post = self._transition(
            (1.0 - is_first) * recurrent_state, None, sample_state=False
        )[1]
        recurrent_state, posterior, posterior_logits = self.dynamic_posterior(
            posterior, recurrent_state, action, embed_proj, is_first, init_post, key
        )
        prior_logits = self.prior_logits(recurrent_state)
        return recurrent_state, posterior, posterior_logits, prior_logits

    def dynamic_posterior(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embed_proj: jnp.ndarray,
        is_first: jnp.ndarray,
        init_posterior: jnp.ndarray,
        key: Optional[jax.Array],
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Sequential core of ``dynamic``: only the posterior chain.

        The transition (prior) model never feeds back into the time loop —
        its logits depend only on the produced recurrent states — so train
        scans run this reduced step and batch :meth:`prior_logits` over the
        whole ``[T, B]`` output afterwards; likewise ``init_posterior`` (the
        prior mode at a zeroed recurrent state, constant) is computed once
        outside. Cuts the per-timestep weight streaming roughly in half.
        """
        action = (1.0 - is_first) * action
        recurrent_state = (1.0 - is_first) * recurrent_state
        posterior = (1.0 - is_first) * posterior + is_first * init_posterior
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        if gumbel is None:
            # same key split as dynamic() (whose k1 sampled the discarded
            # prior) so both paths draw the identical posterior sample stream
            key = jax.random.split(key)[1]
        posterior_logits, posterior = self._representation_projected(
            recurrent_state, embed_proj, key, gumbel=gumbel
        )
        return recurrent_state, posterior, posterior_logits

    def prior_logits(self, recurrent_states: jnp.ndarray) -> jnp.ndarray:
        """Unimixed transition logits — batchable over any leading shape."""
        return uniform_mix(
            self.transition_model(recurrent_states), self.discrete_size, self.unimix
        )

    def imagination(
        self,
        prior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        actions: jnp.ndarray,
        key: Optional[jax.Array],
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """One prior step in imagination (reference :441-457): flat prior in,
        flat sampled prior + new recurrent state out."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([prior, actions], -1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key, gumbel=gumbel)
        return imagined_prior, recurrent_state

    def __call__(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        return self.dynamic(posterior, recurrent_state, action, embedded_obs, is_first, key)


# ---------------------------------------------------------------------------
# world model
# ---------------------------------------------------------------------------


class MLPWithHead(nn.Module):
    """Dense trunk + single linear head (reward / continue / critic shape)."""

    output_dim: int
    mlp_layers: int
    dense_units: int
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            dtype=self.dtype,
        )(x)
        return nn.Dense(self.output_dim, dtype=self.dtype, name="head")(x).astype(jnp.float32)


class WorldModel(nn.Module):
    """Encoder + RSSM + observation/reward/continue heads (the canonical
    container from reference dreamer_v2/agent.py:714-739, reused by DV3).

    Methods are exposed for ``apply(..., method=...)`` so the train step can
    call exactly the piece it needs inside ``lax.scan``.
    """

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels: Sequence[int]  # per-key channel counts (after frame-stack folding)
    mlp_dims: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    stages: int
    encoder_mlp_layers: int
    decoder_mlp_layers: int
    dense_units: int
    recurrent_state_size: int
    stochastic_size: int
    discrete_size: int
    hidden_size: int
    reward_bins: int
    representation_hidden_size: Optional[int] = None
    reward_mlp_layers: Optional[int] = None
    reward_dense_units: Optional[int] = None
    continue_mlp_layers: Optional[int] = None
    continue_dense_units: Optional[int] = None
    layer_norm: bool = True
    unimix: float = 0.01
    cnn_act: Any = "silu"
    dense_act: Any = "silu"
    dtype: Optional[Any] = None

    def setup(self):
        self.encoder = MultiEncoderDV3(
            cnn_keys=self.cnn_keys,
            mlp_keys=self.mlp_keys,
            channels_multiplier=self.channels_multiplier,
            stages=self.stages,
            mlp_layers=self.encoder_mlp_layers,
            dense_units=self.dense_units,
            layer_norm=self.layer_norm,
            cnn_act=self.cnn_act,
            dense_act=self.dense_act,
            dtype=self.dtype,
        )
        # static encoder output width sizes the split posterior trunk kernel
        embed_size = MultiEncoderDV3.output_width(
            self.cnn_keys,
            self.mlp_keys,
            self.image_size,
            self.channels_multiplier,
            self.stages,
            self.dense_units,
        )
        self.rssm = RSSM(
            recurrent_state_size=self.recurrent_state_size,
            stochastic_size=self.stochastic_size,
            discrete_size=self.discrete_size,
            dense_units=self.dense_units,
            hidden_size=self.hidden_size,
            embed_size=embed_size,
            representation_hidden_size=self.representation_hidden_size,
            layer_norm=self.layer_norm,
            unimix=self.unimix,
            activation=self.dense_act,
            dtype=self.dtype,
        )
        if self.cnn_keys:
            self.cnn_decoder = CNNDecoder(
                output_channels=self.cnn_channels,
                channels_multiplier=self.channels_multiplier,
                stages=self.stages,
                image_size=self.image_size,
                layer_norm=self.layer_norm,
                activation=self.cnn_act,
                dtype=self.dtype,
            )
        if self.mlp_keys:
            self.mlp_decoder = MLPDecoder(
                keys=self.mlp_keys,
                output_dims=self.mlp_dims,
                mlp_layers=self.decoder_mlp_layers,
                dense_units=self.dense_units,
                layer_norm=self.layer_norm,
                activation=self.dense_act,
                dtype=self.dtype,
            )
        self.reward_model = MLPWithHead(
            output_dim=self.reward_bins,
            mlp_layers=self.reward_mlp_layers or self.decoder_mlp_layers,
            dense_units=self.reward_dense_units or self.dense_units,
            layer_norm=self.layer_norm,
            activation=self.dense_act,
            dtype=self.dtype,
        )
        self.continue_model = MLPWithHead(
            output_dim=1,
            mlp_layers=self.continue_mlp_layers or self.decoder_mlp_layers,
            dense_units=self.continue_dense_units or self.dense_units,
            layer_norm=self.layer_norm,
            activation=self.dense_act,
            dtype=self.dtype,
        )

    # -- methods for apply(..., method=...) --------------------------------

    def encode(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        return self.encoder(obs)

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        return self.rssm.dynamic(posterior, recurrent_state, action, embedded_obs, is_first, key)

    def project_embed(self, embedded_obs):
        return self.rssm.project_embed(embedded_obs)

    def dynamic_projected(self, posterior, recurrent_state, action, embed_proj, is_first, key):
        return self.rssm.dynamic_projected(
            posterior, recurrent_state, action, embed_proj, is_first, key
        )

    def dynamic_posterior(
        self,
        posterior,
        recurrent_state,
        action,
        embed_proj,
        is_first,
        init_posterior,
        key,
        gumbel=None,
    ):
        return self.rssm.dynamic_posterior(
            posterior, recurrent_state, action, embed_proj, is_first, init_posterior, key, gumbel
        )

    def prior_logits(self, recurrent_states):
        return self.rssm.prior_logits(recurrent_states)

    def imagination(self, prior, recurrent_state, actions, key, gumbel=None):
        return self.rssm.imagination(prior, recurrent_state, actions, key, gumbel=gumbel)

    def initial_posterior(self, recurrent_state: jnp.ndarray) -> jnp.ndarray:
        """Mode of the prior at a fresh recurrent state (player init,
        reference agent.py:516-537)."""
        return self.rssm._transition(recurrent_state, None, sample_state=False)[1]

    def recurrent_step(self, stochastic, actions, recurrent_state):
        return self.rssm.recurrent_model(
            jnp.concatenate([stochastic, actions], -1), recurrent_state
        )

    def representation(self, recurrent_state, embedded_obs, key):
        return self.rssm._representation(recurrent_state, embedded_obs, key)

    def decode(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {}
        if self.cnn_keys:
            rec = self.cnn_decoder(latent)
            if len(self.cnn_keys) > 1:
                parts = jnp.split(rec, np.cumsum(np.asarray(self.cnn_channels))[:-1], axis=-3)
            else:
                parts = [rec]
            out.update({k: v for k, v in zip(self.cnn_keys, parts)})
        if self.mlp_keys:
            out.update(self.mlp_decoder(latent))
        return out

    def reward_logits(self, latent: jnp.ndarray) -> jnp.ndarray:
        return self.reward_model(latent)

    def continue_logits(self, latent: jnp.ndarray) -> jnp.ndarray:
        return self.continue_model(latent)

    def __call__(self, obs, posterior, recurrent_state, action, is_first, key):
        """Init-path: touches every submodule once."""
        embed = self.encoder(obs)
        recurrent_state, posterior, post_logits, prior_logits = self.rssm.dynamic(
            posterior, recurrent_state, action, embed, is_first, key
        )
        latent = jnp.concatenate([posterior, recurrent_state], -1)
        recon = self.decode(latent)
        return (
            recurrent_state,
            posterior,
            post_logits,
            prior_logits,
            recon,
            self.reward_model(latent),
            self.continue_model(latent),
        )


# ---------------------------------------------------------------------------
# actor / critic
# ---------------------------------------------------------------------------


class Actor(nn.Module):
    """DV3 actor (reference agent.py:588-767): dense trunk + one head per
    sub-action (discrete) or a single ``2*sum(dim)`` head (continuous).

    ``__call__`` returns the raw head outputs; distribution construction and
    sampling are pure functions below so they stay usable inside any jitted
    program.
    """

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    dense_units: int = 1024
    mlp_layers: int = 5
    layer_norm: bool = True
    activation: Any = "silu"
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, state: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
            dtype=self.dtype,
        )(state)
        if self.is_continuous:
            return (
                nn.Dense(int(np.sum(self.actions_dim)) * 2, dtype=self.dtype, name="head_0")(x)
                .astype(jnp.float32),
            )
        return tuple(
            nn.Dense(dim, dtype=self.dtype, name=f"head_{i}")(x).astype(jnp.float32)
            for i, dim in enumerate(self.actions_dim)
        )


def resolve_actor_distribution(distribution: str, is_continuous: bool) -> str:
    dist = (distribution or "auto").lower()
    if dist not in ("auto", "normal", "tanh_normal", "discrete", "trunc_normal"):
        raise ValueError(
            "The distribution must be on of: `auto`, `discrete`, `normal`, "
            f"`tanh_normal` and `trunc_normal`. Found: {dist}"
        )
    if dist == "discrete" and is_continuous:
        raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
    if dist == "auto":
        dist = "trunc_normal" if is_continuous else "discrete"
    return dist


def build_actor_dists(
    pre_dist: Sequence[jnp.ndarray],
    is_continuous: bool,
    distribution: str,
    init_std: float = 0.0,
    min_std: float = 0.1,
    unimix: float = 0.01,
) -> List[Any]:
    """Raw head outputs → per-sub-action distributions (reference :697-738)."""
    if is_continuous:
        mean, std = jnp.split(pre_dist[0], 2, axis=-1)
        if distribution == "tanh_normal":
            mean = 5.0 * jnp.tanh(mean / 5.0)
            std = jax.nn.softplus(std + init_std) + min_std
            return [Independent(TanhNormal(mean, std), 1)]
        if distribution == "normal":
            return [Independent(Normal(mean, std), 1)]
        if distribution == "trunc_normal":
            std = 2.0 * jax.nn.sigmoid((std + init_std) / 2.0) + min_std
            return [Independent(TruncatedNormal(jnp.tanh(mean), std, -1.0, 1.0), 1)]
        raise ValueError(f"Unknown continuous distribution '{distribution}'")
    dists = []
    for logits in pre_dist:
        probs = jax.nn.softmax(logits, axis=-1)
        if unimix > 0.0:
            probs = (1.0 - unimix) * probs + unimix / probs.shape[-1]
        dists.append(OneHotCategoricalStraightThrough(logits=jnp.log(probs)))
    return dists


def sample_actor_actions(
    dists: Sequence[Any], is_continuous: bool, key: jax.Array, is_training: bool = True
) -> List[jnp.ndarray]:
    """rsample when training; mode (discrete) / best-of-100 (continuous) for
    greedy evaluation (reference :714-738)."""
    keys = jax.random.split(key, len(dists))
    actions = []
    for d, k in zip(dists, keys):
        if is_training:
            actions.append(d.rsample(k))
        elif is_continuous:
            samples = d.sample(k, (100,))
            log_prob = d.log_prob(samples)
            best = jnp.argmax(log_prob, axis=0)
            actions.append(jnp.take_along_axis(samples, best[None, ..., None], axis=0)[0])
        else:
            actions.append(d.mode)
    return actions


def actor_entropy(dists: Sequence[Any], distribution: str) -> jnp.ndarray:
    """Summed per-head entropy; tanh_normal has no closed form → zeros
    (reference catches NotImplementedError at dreamer_v3.py:330-333)."""
    if distribution == "tanh_normal":
        base = dists[0].base.base  # Independent→TanhNormal→Normal
        return jnp.zeros(base.loc.shape[:-1], base.loc.dtype)
    return sum(d.entropy() for d in dists)


def add_exploration_noise(
    actions: Sequence[jnp.ndarray],
    expl_amount: jnp.ndarray,
    is_continuous: bool,
    key: jax.Array,
) -> List[jnp.ndarray]:
    """ε-exploration (reference :748-767): Gaussian noise clipped to [-1,1]
    (continuous) or uniform-resample with prob ε (discrete). ``expl_amount``
    is a dynamic scalar so decay never recompiles."""
    if is_continuous:
        cat = jnp.concatenate(actions, -1)
        noisy = jnp.clip(cat + expl_amount * jax.random.normal(key, cat.shape), -1.0, 1.0)
        return [jnp.where(expl_amount > 0.0, noisy, cat)]
    out = []
    keys = jax.random.split(key, 2 * len(actions))
    for i, act in enumerate(actions):
        rand = OneHotCategorical(logits=jnp.zeros_like(act)).sample(keys[2 * i])
        take = jax.random.uniform(keys[2 * i + 1], act.shape[:-1] + (1,)) < expl_amount
        out.append(jnp.where(take, rand, act))
    return out


# ---------------------------------------------------------------------------
# Hafner initialization
# ---------------------------------------------------------------------------

_TRUNC_STD_FACTOR = 0.87962566103423978


def _fans(shape: Tuple[int, ...]) -> Tuple[float, float]:
    if len(shape) == 2:  # dense [in, out]
        return float(shape[0]), float(shape[1])
    if len(shape) == 4:  # conv [kh, kw, in, out] (flax layout)
        space = shape[0] * shape[1]
        return float(space * shape[2]), float(space * shape[3])
    return float(np.prod(shape[:-1])), float(shape[-1])


def hafner_initialization(
    params: Dict[str, Any], key: jax.Array, uniform_heads: Sequence[Tuple[str, float]] = ()
) -> Dict[str, Any]:
    """Re-initialize every kernel with the Hafner scheme (reference
    dreamer_v3/utils.py init_weights/uniform_init_weights + the head overrides
    in build_models :1109-1119).

    - default: truncated normal, std = sqrt(1/mean(fan_in, fan_out)) / 0.8796,
      truncated at ±2σ;
    - ``uniform_heads``: (path-regex, scale) pairs; matching kernels get
      U(−limit, limit) with limit = sqrt(3·scale/mean(fan)); scale 0 → zeros.

    Biases / norm params keep flax defaults (zeros / ones), which is what the
    reference sets too.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    n = len(flat)
    keys = jax.random.split(key, max(n, 1))
    compiled = [(re.compile(pat), scale) for pat, scale in uniform_heads]

    def path_str(path) -> str:
        return "/".join(getattr(p, "key", str(p)) for p in path)

    new_leaves = {}
    for i, (path, leaf) in enumerate(flat):
        p = path_str(path)
        if not p.endswith("kernel") or leaf.ndim < 2:
            new_leaves[p] = leaf
            continue
        fan_in, fan_out = _fans(leaf.shape)
        denom = (fan_in + fan_out) / 2.0
        matched = None
        for pat, scale in compiled:
            if pat.search(p):
                matched = scale
                break
        if matched is not None:
            if matched == 0.0:
                new_leaves[p] = jnp.zeros_like(leaf)
            else:
                limit = math.sqrt(3.0 * matched / denom)
                new_leaves[p] = jax.random.uniform(
                    keys[i], leaf.shape, leaf.dtype, -limit, limit
                )
        else:
            std = math.sqrt(1.0 / denom) / _TRUNC_STD_FACTOR
            new_leaves[p] = std * jax.random.truncated_normal(
                keys[i], -2.0, 2.0, leaf.shape, leaf.dtype
            )

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: new_leaves[path_str(path)], params
    )


# DV3 head overrides (reference build_models :1109-1119)
WM_UNIFORM_HEADS = (
    (r"reward_model/head/", 0.0),
    (r"rssm/transition_model/head/", 1.0),
    (r"rssm/representation_model/head/", 1.0),
    (r"continue_model/head/", 1.0),
    (r"mlp_decoder/head_", 1.0),
    (r"cnn_decoder/head/", 1.0),
)
ACTOR_UNIFORM_HEADS = ((r"head_\d+/", 1.0),)
CRITIC_UNIFORM_HEADS = ((r"head/", 0.0),)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    observation_space,
    key: jax.Array,
) -> Tuple[WorldModel, Actor, MLPWithHead, Dict[str, Any]]:
    """Construct module defs + initialized params (reference build_models,
    agent.py:900-1144). Returns ``(world_model, actor, critic, params)`` with
    ``params = {world_model, actor, critic, target_critic}``."""
    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    screen = int(cfg.env.screen_size)
    stages = int(np.log2(screen)) - 2
    # fabric.precision=bf16-mixed: bf16 compute with f32 params and f32
    # losses/logits (heads cast back); 32-true keeps everything f32
    from sheeprl_tpu.fabric import compute_dtype_from_precision

    compute_dtype = compute_dtype_from_precision(cfg.fabric.get("precision", "32-true"))
    cnn_channels = [
        int(np.prod(observation_space[k].shape[:-2])) for k in cnn_keys
    ]
    mlp_dims = [int(np.prod(observation_space[k].shape)) for k in mlp_keys]

    world_model = WorldModel(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_channels=cnn_channels,
        mlp_dims=mlp_dims,
        image_size=(screen, screen),
        channels_multiplier=int(wm_cfg.encoder.cnn_channels_multiplier),
        stages=stages,
        encoder_mlp_layers=int(wm_cfg.encoder.mlp_layers),
        decoder_mlp_layers=int(wm_cfg.observation_model.mlp_layers),
        dense_units=int(wm_cfg.encoder.dense_units),
        recurrent_state_size=int(wm_cfg.recurrent_model.recurrent_state_size),
        stochastic_size=int(wm_cfg.stochastic_size),
        discrete_size=int(wm_cfg.discrete_size),
        hidden_size=int(wm_cfg.transition_model.hidden_size),
        representation_hidden_size=int(wm_cfg.representation_model.hidden_size),
        reward_bins=int(wm_cfg.reward_model.bins),
        reward_mlp_layers=int(wm_cfg.reward_model.mlp_layers),
        reward_dense_units=int(wm_cfg.reward_model.dense_units),
        continue_mlp_layers=int(wm_cfg.discount_model.mlp_layers),
        continue_dense_units=int(wm_cfg.discount_model.dense_units),
        layer_norm=bool(cfg.algo.layer_norm),
        unimix=float(cfg.algo.unimix),
        cnn_act=cfg.algo.cnn_act,
        dense_act=cfg.algo.dense_act,
        dtype=compute_dtype,
    )
    latent_size = (
        int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
        + int(wm_cfg.recurrent_model.recurrent_state_size)
    )
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=resolve_actor_distribution(
            cfg.distribution.get("type", "auto"), is_continuous
        ),
        dense_units=int(cfg.algo.actor.dense_units),
        mlp_layers=int(cfg.algo.actor.mlp_layers),
        layer_norm=bool(cfg.algo.actor.layer_norm),
        activation=cfg.algo.actor.dense_act,
        dtype=compute_dtype,
    )
    critic = MLPWithHead(
        output_dim=int(cfg.algo.critic.bins),
        mlp_layers=int(cfg.algo.critic.mlp_layers),
        dense_units=int(cfg.algo.critic.dense_units),
        layer_norm=bool(cfg.algo.critic.layer_norm),
        activation=cfg.algo.critic.dense_act,
        dtype=compute_dtype,
    )

    k_wm, k_actor, k_critic, k_hw, k_ha, k_hc, k_s = jax.random.split(key, 7)
    dummy_obs = {}
    for k, ch in zip(cnn_keys, cnn_channels):
        dummy_obs[k] = jnp.zeros((1, ch, screen, screen), jnp.float32)
    for k, dim in zip(mlp_keys, mlp_dims):
        dummy_obs[k] = jnp.zeros((1, dim), jnp.float32)
    stoch = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec = int(wm_cfg.recurrent_model.recurrent_state_size)
    act_dim = int(np.sum(actions_dim))

    wm_params = world_model.init(
        k_wm,
        dummy_obs,
        jnp.zeros((1, stoch)),
        jnp.zeros((1, rec)),
        jnp.zeros((1, act_dim)),
        jnp.zeros((1, 1)),
        k_s,
    )["params"]
    actor_params = actor.init(k_actor, jnp.zeros((1, latent_size)))["params"]
    critic_params = critic.init(k_critic, jnp.zeros((1, latent_size)))["params"]

    if bool(cfg.algo.hafner_initialization):
        wm_params = hafner_initialization(wm_params, k_hw, WM_UNIFORM_HEADS)
        actor_params = hafner_initialization(actor_params, k_ha, ACTOR_UNIFORM_HEADS)
        critic_params = hafner_initialization(critic_params, k_hc, CRITIC_UNIFORM_HEADS)

    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
    }
    return world_model, actor, critic, params


# ---------------------------------------------------------------------------
# functional player (reference PlayerDV3, agent.py:460-585)
# ---------------------------------------------------------------------------


def build_player_fns(
    world_model: WorldModel,
    actor: Actor,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    packed_template: Any = None,
):
    """Pure jitted player functions over an explicit state pytree
    ``{"actions", "recurrent", "stochastic"}`` (each ``[n_envs, ...]``).

    Replaces the reference's mutable PlayerDV3 (agent.py:516-585); per-env
    resets are ``jnp.where`` masks so vectorized-env episode ends never leave
    jit.
    """
    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)
    unimix = float(cfg.algo.unimix)
    rec_size = int(cfg.algo.world_model.recurrent_model.recurrent_state_size)
    act_dim = int(np.sum(actions_dim))
    # MineDojo envs carry per-step validity masks: route sampling and
    # exploration noise through the mask-aware actor (reference dispatches a
    # MinedojoActor subclass via cfg.algo.actor.cls; here the same head
    # layout takes a `masks` kwarg — minedojo_actor.py)
    minedojo = "minedojo" in str(cfg.env.wrapper.get("_target_", "") or "").lower()

    def init_states(wm_params, n_envs: int):
        recurrent = jnp.tanh(jnp.zeros((n_envs, rec_size)))
        stochastic = world_model.apply(
            {"params": wm_params}, recurrent, method=WorldModel.initial_posterior
        )
        return {
            "actions": jnp.zeros((n_envs, act_dim)),
            "recurrent": recurrent,
            "stochastic": stochastic,
        }

    def reset_states(wm_params, state, reset_mask):
        """``reset_mask``: [n_envs, 1] float — 1 resets that env's state."""
        fresh = init_states(wm_params, state["actions"].shape[0])
        return jax.tree_util.tree_map(
            lambda f, s: reset_mask * f + (1.0 - reset_mask) * s, fresh, state
        )

    def _step(wm_params, actor_params, state, obs, key, is_training: bool, masks=None):
        embed = world_model.apply({"params": wm_params}, obs, method=WorldModel.encode)
        recurrent = world_model.apply(
            {"params": wm_params},
            state["stochastic"],
            state["actions"],
            state["recurrent"],
            method=WorldModel.recurrent_step,
        )
        k_repr, k_act = jax.random.split(key)
        _, stochastic = world_model.apply(
            {"params": wm_params}, recurrent, embed, k_repr, method=WorldModel.representation
        )
        latent = jnp.concatenate([stochastic, recurrent], -1)
        pre_dist = actor.apply({"params": actor_params}, latent)
        if minedojo and masks is not None:
            from sheeprl_tpu.algos.dreamer_v3.minedojo_actor import sample_minedojo_actions

            actions, _ = sample_minedojo_actions(
                pre_dist, masks, k_act, unimix, is_training
            )
        else:
            dists = build_actor_dists(
                pre_dist, is_continuous, distribution, init_std, min_std, unimix
            )
            actions = sample_actor_actions(dists, is_continuous, k_act, is_training)
        new_state = {
            "actions": jnp.concatenate(actions, -1),
            "recurrent": recurrent,
            "stochastic": stochastic,
        }
        return actions, new_state

    @jax.jit
    def greedy_action(wm_params, actor_params, state, obs, key, masks=None):
        return _step(wm_params, actor_params, state, obs, key, is_training=False, masks=masks)

    @jax.jit
    def exploration_action(wm_params, actor_params, state, obs, key, expl_amount, masks=None):
        k_step, k_expl = jax.random.split(key)
        actions, new_state = _step(
            wm_params, actor_params, state, obs, k_step, is_training=True, masks=masks
        )
        if minedojo and masks is not None:
            from sheeprl_tpu.algos.dreamer_v3.minedojo_actor import (
                add_minedojo_exploration_noise,
            )

            expl = add_minedojo_exploration_noise(actions, expl_amount, masks, k_expl)
        else:
            expl = add_exploration_noise(actions, expl_amount, is_continuous, k_expl)
        new_state = dict(new_state, actions=jnp.concatenate(expl, -1))
        return expl, new_state

    # raw-obs variants: normalization happens INSIDE the jit, so acting is a
    # single dispatch taking native-dtype (uint8 pixel) host arrays. On a
    # remote-attached device the eager normalize of the plain variants would
    # cost one extra round trip per obs key per env step, and f32 pixels are
    # 4x the uint8 upload.
    cnn_keys = tuple(cfg.cnn_keys.encoder)

    def _normalize(raw_obs):
        from sheeprl_tpu.algos.dreamer_v3.utils import normalize_obs_jnp

        return normalize_obs_jnp(raw_obs, cnn_keys)

    @jax.jit
    def greedy_action_raw(wm_params, actor_params, state, raw_obs, key, masks=None):
        return _step(
            wm_params, actor_params, state, _normalize(raw_obs), key,
            is_training=False, masks=masks,
        )

    @jax.jit
    def exploration_action_raw(
        wm_params, actor_params, state, raw_obs, key, expl_amount, masks=None
    ):
        return exploration_action(
            wm_params, actor_params, state, _normalize(raw_obs), key, expl_amount,
            masks=masks,
        )

    fns = {
        "init_states": init_states,
        "reset_states": jax.jit(reset_states),
        "greedy_action": greedy_action,
        "exploration_action": exploration_action,
        "greedy_action_raw": greedy_action_raw,
        "exploration_action_raw": exploration_action_raw,
    }

    # packed variants: all acting params arrive as ONE flat vector and are
    # unraveled inside the jit. On a remote-attached device the per-call
    # overhead scales with the number of argument buffers (~1 s/call measured
    # for the full param tree over a high-latency link vs ~120 ms for one);
    # the train burst emits this packed vector directly (dreamer_v3.py).
    if packed_template is not None:
        from jax.flatten_util import ravel_pytree

        _, unravel_packed = ravel_pytree(packed_template)

        @jax.jit
        def exploration_action_packed(packed, state, raw_obs, key, expl_amount, masks=None):
            tree = unravel_packed(packed)
            return exploration_action(
                tree["wm"], tree["actor"], state, _normalize(raw_obs), key,
                expl_amount, masks=masks,
            )

        @jax.jit
        def greedy_action_packed(packed, state, raw_obs, key, masks=None):
            tree = unravel_packed(packed)
            return _step(
                tree["wm"], tree["actor"], state, _normalize(raw_obs), key,
                is_training=False, masks=masks,
            )

        @jax.jit
        def reset_states_packed(packed, state, reset_mask):
            tree = unravel_packed(packed)
            return reset_states(tree["wm"], state, reset_mask)

        @partial(jax.jit, static_argnums=(1,))
        def init_states_packed(packed, n_envs: int):
            # the burst-acting host callback applies episode resets as
            # mask * fresh + (1 - mask) * state with a host copy of this
            # fresh state, refreshed once per params version
            tree = unravel_packed(packed)
            return init_states(tree["wm"], n_envs)

        fns.update(
            exploration_action_packed=exploration_action_packed,
            greedy_action_packed=greedy_action_packed,
            reset_states_packed=reset_states_packed,
            init_states_packed=init_states_packed,
        )
    return fns
