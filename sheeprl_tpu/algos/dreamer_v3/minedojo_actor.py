"""Mask-aware MineDojo actor (reference ``sheeprl/algos/dreamer_v3/agent.py``
MinedojoActor :770-897, and the DV2 variant it subclasses).

MineDojo exposes per-step validity masks (``mask_action_type``,
``mask_craft_smelt``, ``mask_equip_place``, ``mask_destroy`` — see
``envs/minedojo.py``); the actor must sample the three-head action
(action-type, craft-arg, item-arg) so that

- invalid action types are never selected,
- the craft-arg head is masked by ``mask_craft_smelt`` *only when* the
  sampled action type is craft (id 15),
- the item-arg head is masked by ``mask_equip_place`` for equip/place
  (16/17) and by ``mask_destroy`` for destroy (18).

The reference implements the conditioning with Python loops over the
``[T, B]`` grid; here it is branchless ``jnp.where`` masking over the whole
batch, so the masked actor stays inside the jitted player/imagination
programs (SURVEY.md "hard parts": mask-dependent Minedojo actors must
become branchless to stay jittable).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.agent import uniform_mix
from sheeprl_tpu.distributions import OneHotCategorical, OneHotCategoricalStraightThrough

_NEG_INF = -1e9  # softmax-safe -inf: keeps masked logits finite under jit

CRAFT_ACTION = 15
EQUIP_ACTION = 16
PLACE_ACTION = 17
DESTROY_ACTION = 18


def _mask_logits(logits: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(valid, logits, _NEG_INF)


def masked_action_type_logits(logits: jnp.ndarray, masks: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Head 0: invalid action types are unreachable (reference :820-823)."""
    return _mask_logits(logits, masks["mask_action_type"].astype(bool))


def masked_arg_logits(
    head: int,
    logits: jnp.ndarray,
    functional_action: jnp.ndarray,
    masks: Dict[str, jnp.ndarray],
) -> jnp.ndarray:
    """Heads 1/2 conditioned on the *sampled* action type
    (reference :824-843), branchlessly over the batch.

    ``functional_action``: integer ``[...]`` action-type ids.
    """
    if head == 1:
        is_craft = (functional_action == CRAFT_ACTION)[..., None]
        valid = jnp.logical_or(
            jnp.logical_not(is_craft), masks["mask_craft_smelt"].astype(bool)
        )
        return _mask_logits(logits, valid)
    if head == 2:
        is_equip_place = jnp.logical_or(
            functional_action == EQUIP_ACTION, functional_action == PLACE_ACTION
        )[..., None]
        is_destroy = (functional_action == DESTROY_ACTION)[..., None]
        valid = jnp.logical_and(
            jnp.logical_or(jnp.logical_not(is_equip_place), masks["mask_equip_place"].astype(bool)),
            jnp.logical_or(jnp.logical_not(is_destroy), masks["mask_destroy"].astype(bool)),
        )
        return _mask_logits(logits, valid)
    raise ValueError(f"masked_arg_logits handles heads 1 and 2, got {head}")


def sample_minedojo_actions(
    pre_dist: Sequence[jnp.ndarray],
    masks: Optional[Dict[str, jnp.ndarray]],
    key: jax.Array,
    unimix: float = 0.01,
    is_training: bool = True,
) -> Tuple[List[jnp.ndarray], List[Any]]:
    """Sequentially sample the three MineDojo heads with mask conditioning
    (reference forward :801-853). Returns ``(actions, dists)``."""
    if masks is None:
        masks = {}
    keys = jax.random.split(key, len(pre_dist))
    actions: List[jnp.ndarray] = []
    dists: List[Any] = []
    functional_action = None
    for i, logits in enumerate(pre_dist):
        logits = uniform_mix(logits, logits.shape[-1], unimix)
        if masks:
            if i == 0:
                logits = masked_action_type_logits(logits, masks)
            else:
                logits = masked_arg_logits(i, logits, functional_action, masks)
        dist = OneHotCategoricalStraightThrough(logits=logits)
        act = dist.rsample(keys[i]) if is_training else dist.mode
        actions.append(act)
        dists.append(dist)
        if functional_action is None:
            functional_action = jnp.argmax(act, axis=-1)
    return actions, dists


def add_minedojo_exploration_noise(
    actions: Sequence[jnp.ndarray],
    expl_amount: jnp.ndarray,
    masks: Optional[Dict[str, jnp.ndarray]],
    key: jax.Array,
) -> List[jnp.ndarray]:
    """ε-exploration that still respects the env constraints (reference
    add_exploration_noise :855-897): uniform resampling draws only from the
    *valid* actions, and when the resampled action type becomes a functional
    action (craft/equip/place/destroy) the argument heads are forced to
    resample too so the composite action stays consistent."""
    if masks is None:
        masks = {}
    out: List[jnp.ndarray] = []
    functional_action = jnp.argmax(actions[0], axis=-1)
    keys = jax.random.split(key, 2 * len(actions))
    type_changed = None
    for i, act in enumerate(actions):
        logits = jnp.zeros_like(act)
        if masks:
            if i == 0:
                logits = masked_action_type_logits(logits, masks)
            else:
                logits = masked_arg_logits(i, logits, functional_action, masks)
        rand = OneHotCategorical(logits=logits).sample(keys[2 * i])
        take = jax.random.uniform(keys[2 * i + 1], act.shape[:-1] + (1,)) < expl_amount
        if i == 0:
            new0 = jnp.where(take, rand, act)
            new_functional = jnp.argmax(new0, axis=-1)
            # forced-resample condition for the argument heads (reference
            # expl_amount = 2 hack :883-889)
            type_changed = jnp.logical_and(
                new_functional != functional_action,
                jnp.logical_and(new_functional >= CRAFT_ACTION, new_functional <= DESTROY_ACTION),
            )[..., None]
            functional_action = new_functional
            out.append(new0)
        else:
            take = jnp.logical_or(take, type_changed)
            out.append(jnp.where(take, rand, act))
    return out
