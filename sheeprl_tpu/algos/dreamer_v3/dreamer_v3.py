"""DreamerV3 — the flagship model-based algorithm.

Behavioral contract from the reference ``sheeprl/algos/dreamer_v3/dreamer_v3.py``
(train :49-378, main :381-832): sequence-replay world-model learning
(posterior scan over T=64), 15-step imagination for actor-critic learning with
percentile-normalized λ-returns, two-hot critic with EMA target regularizer,
ε-greedy env interaction gated by ``learning_starts``/``train_every``.

TPU-native design (NOT a translation):

- **One jitted SPMD program per gradient step.** The reference runs three
  separate backward/step passes plus a Python GRU loop per batch; here the
  target-EMA, world-model update, imagination rollout, actor update, critic
  update, and Moments state all live in a single ``shard_map``-ped jit with
  the batch dim sharded over the mesh's ``data`` axis. Sequence (T) and
  horizon (H) loops are ``lax.scan``; XLA fuses the GRU cell across steps.
- **Gradient psum via shardings.** Each of the three losses takes
  ``lax.pmean`` on its grads over the data axis — the DDP allreduce —
  and the Moments percentile EMA all-gathers λ-returns across the mesh
  (reference utils.py:61), keeping bitwise 1-vs-N invariance of the math.
- **Stateless cadences.** Target-EMA cadence (tau ∈ {0, τ, 1}) and
  exploration amount enter as dynamic scalars: no recompiles.
- The whole agent (3 param trees + target + 3 optax states + moments) is one
  pytree, donated through the step: params stay resident in HBM.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    WorldModel,
    build_actor_dists,
    build_agent,
    build_player_fns,
    actor_entropy,
    sample_actor_actions,
)
from sheeprl_tpu.algos.dreamer_v3.loss import continue_distribution, reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    compute_lambda_values,
    init_moments,
    prepare_obs,
    test,
    update_moments,
)
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.distributions import MSEDistribution, SymlogDistribution, TwoHotEncodingDistribution
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.plane import train_gated_burst_plan
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    LoopProbe,
    learn_probes,
    log_sps_metrics,
    probes_enabled,
    profile_tick,
    set_shard_footprint,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.parallel.shard import measured_bytes_per_device
from sheeprl_tpu.train import (
    TrainProgram,
    build_train_burst,
    metric_fetch_gate,
    run_train_burst,
    tau_schedule,
)
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs

sg = jax.lax.stop_gradient


def build_train_fn(
    world_model: WorldModel,
    actor: Actor,
    critic,
    world_tx: optax.GradientTransformation,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
    cfg,
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    plan=None,
):
    """Compile one full DreamerV3 gradient step as a single SPMD program.

    Returns ``train_step(agent_state, data, key, tau) -> (agent_state,
    metrics)`` where ``data`` leaves are ``[T, B_total, ...]`` (B sharded over
    the mesh) and ``tau`` is the dynamic target-EMA coefficient (0 = skip).

    ``plan`` (a :class:`~sheeprl_tpu.parallel.shard.ShardingPlan` over the
    agent-state tree, from ``fabric.shard_plan``) switches the program onto
    the ``{'data','model'}`` mesh as ONE GSPMD program: no manual shard_map
    region at all — ``axis=None`` turns the per-shard gradient pmean and the
    rank-decorrelating fold_in into identities (the loss already spans the
    global batch, so its gradient IS the all-reduced gradient), params and
    optimizer state enter via ``in_shardings``/``out_shardings`` with the
    plan's model-axis specs, and XLA inserts every collective (batch-dim
    all-reduces on the data axis, all-gather/reduce-scatter on the model
    axis). This sidesteps the jax-0.4-era partitioner, which CHECK-fails on
    ``lax.scan`` inside a partially-manual (``auto=``) shard_map region.
    ``plan=None`` keeps the manual data-parallel shard_map program
    byte-identical to the pure data-parallel runtime.
    """
    data_axis = fabric.data_axis
    axis = data_axis if plan is None else None
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    mlp_keys = tuple(cfg.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.mlp_keys.decoder)
    wm_cfg = cfg.algo.world_model
    stoch_flat = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    kl_dynamic = float(wm_cfg.kl_dynamic)
    kl_representation = float(wm_cfg.kl_representation)
    kl_free_nats = float(wm_cfg.kl_free_nats)
    kl_regularizer = float(wm_cfg.kl_regularizer)
    continue_scale = float(wm_cfg.continue_scale_factor)
    ent_coef = float(cfg.algo.actor.ent_coef)
    from sheeprl_tpu.algos.dreamer_v3.agent import resolve_actor_distribution

    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)
    unimix = float(cfg.algo.unimix)
    moments_cfg = cfg.algo.actor.moments
    m_decay = float(moments_cfg.decay)
    m_max = float(moments_cfg.max)
    m_low = float(moments_cfg.percentile.low)
    m_high = float(moments_cfg.percentile.high)
    dims = tuple(int(d) for d in actions_dim)
    splits = list(np.cumsum(dims)[:-1])
    learn_on = probes_enabled(cfg)
    learn_clips = {
        "world_model": clip_norm_of(world_tx),
        "actor": clip_norm_of(actor_tx),
        "critic": clip_norm_of(critic_tx),
    }

    def wm_apply(params, method, *args):
        return world_model.apply({"params": params}, *args, method=method)

    # ------------------------------------------------------------------
    # world-model loss (reference train :104-194)
    # ------------------------------------------------------------------

    def wm_loss_fn(wm_params, data, key):
        T, B = data["rewards"].shape[:2]
        batch_obs = {k: data[k] / 255.0 for k in cnn_keys}
        batch_obs.update({k: data[k] for k in mlp_keys})
        is_first = data["is_first"].at[0].set(1.0)
        # shift: the action column becomes "action that led here"
        batch_actions = jnp.concatenate(
            [jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], axis=0
        )
        embedded = wm_apply(wm_params, WorldModel.encode, batch_obs)
        # hoist the embed half of the posterior trunk out of the time scan:
        # one [T*B, E]×[E, H] matmul here instead of T sequential [B, E]×[E, H]
        embed_proj = wm_apply(wm_params, WorldModel.project_embed, embedded)
        # the is_first reset posterior is the prior mode at a zeroed recurrent
        # state — a constant, computed once (broadcast over B inside the scan)
        init_post = wm_apply(
            wm_params, WorldModel.initial_posterior, jnp.zeros((1, rec_size))
        )

        def step(carry, inp):
            posterior, recurrent = carry
            action, eproj, first, g = inp
            recurrent, posterior, post_logits = world_model.apply(
                {"params": wm_params},
                posterior,
                recurrent,
                action,
                eproj,
                first,
                init_post,
                None,
                g,
                method=WorldModel.dynamic_posterior,
            )
            return (posterior, recurrent), (recurrent, posterior, post_logits)

        # pre-draw the posterior sampling noise for the whole sequence in one
        # vectorized call; the scan body is left with add+argmax only
        gumbels = jax.random.gumbel(key, (T, B, S, D))
        (_, _), (recurrents, posteriors, post_logits) = jax.lax.scan(
            step,
            (jnp.zeros((B, stoch_flat)), jnp.zeros((B, rec_size))),
            (batch_actions, embed_proj, is_first, gumbels),
        )
        # prior (transition) logits never feed back into the loop: batch them
        # over the whole [T, B] recurrent-state sequence after the scan
        prior_logits = wm_apply(wm_params, WorldModel.prior_logits, recurrents)
        latents = jnp.concatenate([posteriors, recurrents], -1)
        recon = wm_apply(wm_params, WorldModel.decode, latents)
        po = {k: MSEDistribution(recon[k], dims=3) for k in cnn_dec_keys}
        po.update({k: SymlogDistribution(recon[k], dims=1) for k in mlp_dec_keys})
        pr = TwoHotEncodingDistribution(
            wm_apply(wm_params, WorldModel.reward_logits, latents), dims=1
        )
        pc = continue_distribution(
            wm_apply(wm_params, WorldModel.continue_logits, latents)
        )
        loss, metrics = reconstruction_loss(
            po,
            batch_obs,
            pr,
            data["rewards"],
            prior_logits.reshape(T, B, S, D),
            post_logits.reshape(T, B, S, D),
            kl_dynamic,
            kl_representation,
            kl_free_nats,
            kl_regularizer,
            pc,
            1.0 - data["dones"],
            continue_scale,
        )
        return loss, (metrics, sg(posteriors), sg(recurrents))

    # ------------------------------------------------------------------
    # actor loss via imagination (reference train :230-345)
    # ------------------------------------------------------------------

    # A fused Pallas rollout kernel lived here through round 3 (VMEM-resident
    # weights over the whole horizon; 1.6x over the lax scan standalone) but
    # never beat the lax path in-graph: the custom-call scheduling barrier —
    # XLA cannot overlap async weight prefetches across a pallas region —
    # plus per-step pack gathers cost more than the kernel saved (14.67 vs
    # 14.55 ms at the S preset, bf16). Retired in round 4; the lax scan IS
    # the fast path. History: ops/imagination.py before commit 5430c2d.
    S, D = int(wm_cfg.stochastic_size), int(wm_cfg.discrete_size)

    def imagination_rollout(wm_params, actor_params, posteriors, recurrents, key):
        """15-step prior rollout from every (t, b) posterior. Returns
        ``(trajectories [H+1, BT, L], actions [H+1, BT, A])``.

        Gradients flow through the actor's straight-through / rsample
        actions (needed by the continuous dynamics-backprop objective)."""
        prior = posteriors.reshape(-1, stoch_flat)
        recurrent = recurrents.reshape(-1, rec_size)
        latent0 = jnp.concatenate([prior, recurrent], -1)

        def policy(latent, k):
            pre = actor.apply({"params": actor_params}, sg(latent))
            dists = build_actor_dists(
                pre, is_continuous, distribution, init_std, min_std, unimix
            )
            return jnp.concatenate(
                sample_actor_actions(dists, is_continuous, k, True), -1
            )

        k0, key = jax.random.split(key)
        a0 = policy(latent0, k0)

        def step(carry, inp):
            prior, recurrent, action = carry
            g_img, k_act = inp
            prior, recurrent = world_model.apply(
                {"params": wm_params},
                prior,
                recurrent,
                action,
                None,
                g_img,
                method=WorldModel.imagination,
            )
            latent = jnp.concatenate([prior, recurrent], -1)
            action = policy(latent, k_act)
            return (prior, recurrent, action), (latent, action)

        # prior-sampling noise for the whole horizon drawn in one call; only
        # the actor's (distribution-dependent) sampling still consumes keys
        k_gum, key = jax.random.split(key)
        gumbels = jax.random.gumbel(k_gum, (horizon, prior.shape[0], S, D))
        keys = jax.random.split(key, horizon)
        _, (latents, acts) = jax.lax.scan(step, (prior, recurrent, a0), (gumbels, keys))
        trajectories = jnp.concatenate([latent0[None], latents], 0)
        actions = jnp.concatenate([a0[None], acts], 0)
        return trajectories, actions

    def actor_loss_fn(actor_params, wm_params, critic_params, posteriors, recurrents,
                      true_continue, moments_state, key):
        traj, imagined_actions = imagination_rollout(
            wm_params, actor_params, posteriors, recurrents, key
        )
        predicted_values = TwoHotEncodingDistribution(
            critic.apply({"params": critic_params}, traj), dims=1
        ).mean
        predicted_rewards = TwoHotEncodingDistribution(
            wm_apply(wm_params, WorldModel.reward_logits, traj), dims=1
        ).mean
        continues = continue_distribution(
            wm_apply(wm_params, WorldModel.continue_logits, traj)
        ).base.mode
        continues = jnp.concatenate([true_continue[None], continues[1:]], 0)

        lambda_values = compute_lambda_values(
            predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda
        )
        discount = sg(jnp.cumprod(continues * gamma, axis=0) / gamma)

        pre = actor.apply({"params": actor_params}, sg(traj))
        policies = build_actor_dists(
            pre, is_continuous, distribution, init_std, min_std, unimix
        )

        baseline = predicted_values[:-1]
        new_moments, offset, invscale = update_moments(
            moments_state, lambda_values, m_decay, m_max, m_low, m_high, axis_name=axis
        )
        advantage = (lambda_values - offset) / invscale - (baseline - offset) / invscale

        if is_continuous:
            objective = advantage
        else:
            per_head = [
                p.log_prob(sg(a))[..., None][:-1]
                for p, a in zip(policies, jnp.split(imagined_actions, splits, axis=-1))
            ]
            objective = sum(per_head) * sg(advantage)
        entropy = ent_coef * actor_entropy(policies, distribution)
        policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[..., None][:-1]))
        aux = {
            "trajectories": sg(traj),
            "lambda_values": sg(lambda_values),
            "discount": discount,
            "moments": new_moments,
            "Loss/policy_loss": policy_loss,
            "User/LambdaValues": jnp.mean(sg(lambda_values)),
            "User/Advantages": jnp.mean(sg(advantage)),
            "User/Entropy": jnp.mean(sg(entropy)),
            "User/PredictedRewards": jnp.mean(sg(predicted_rewards)),
            "User/PredictedValues": jnp.mean(sg(predicted_values)),
        }
        return policy_loss, aux

    # ------------------------------------------------------------------
    # critic loss (reference train :348-370)
    # ------------------------------------------------------------------

    def critic_loss_fn(critic_params, target_params, traj, lambda_values, discount):
        qv = TwoHotEncodingDistribution(
            critic.apply({"params": critic_params}, traj[:-1]), dims=1
        )
        target_values = TwoHotEncodingDistribution(
            critic.apply({"params": target_params}, traj[:-1]), dims=1
        ).mean
        value_loss = -qv.log_prob(lambda_values) - qv.log_prob(sg(target_values))
        return jnp.mean(value_loss * discount[:-1, ..., 0])

    # ------------------------------------------------------------------
    # the fused step
    # ------------------------------------------------------------------

    def local_step(agent_state, data, key, tau):
        # de-correlate sampling noise across shards: each device works on a
        # different slice of the batch and must draw different latents
        if axis is not None:
            # manual data-parallel program: decorrelate the per-shard noise
            # (the global GSPMD program draws [B_total] noise from one key)
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        params = agent_state["params"]
        opt = agent_state["opt"]

        # target critic EMA, dynamic cadence (reference main :731-735)
        target = jax.tree_util.tree_map(
            lambda c, t: tau * c + (1.0 - tau) * t,
            params["critic"],
            params["target_critic"],
        )

        k_wm, k_img = jax.random.split(key)

        # -- world model update
        (wm_loss, (wm_metrics, posteriors, recurrents)), wm_grads = jax.value_and_grad(
            wm_loss_fn, has_aux=True
        )(params["world_model"], data, k_wm)
        wm_grads = pmean(wm_grads, axis)
        wm_updates, wm_opt = world_tx.update(wm_grads, opt["world_model"], params["world_model"])
        wm_params = optax.apply_updates(params["world_model"], wm_updates)

        # -- actor update (imagination from the *updated* world model, as the
        # reference's in-place optimizer.step implies)
        true_continue = (1.0 - data["dones"]).reshape(-1, 1)
        (actor_loss, aux), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            params["actor"],
            wm_params,
            params["critic"],
            posteriors,
            recurrents,
            true_continue,
            agent_state["moments"],
            k_img,
        )
        actor_grads = pmean(actor_grads, axis)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt["actor"], params["actor"])
        actor_params = optax.apply_updates(params["actor"], actor_updates)

        # -- critic update
        critic_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"],
            target,
            aux["trajectories"],
            aux["lambda_values"],
            aux["discount"],
        )
        critic_grads = pmean(critic_grads, axis)
        critic_updates, critic_opt = critic_tx.update(critic_grads, opt["critic"], params["critic"])
        critic_params = optax.apply_updates(params["critic"], critic_updates)

        metrics = dict(wm_metrics)
        metrics.update(
            {
                k: v
                for k, v in aux.items()
                if k not in ("trajectories", "lambda_values", "discount", "moments")
            }
        )
        metrics["Loss/value_loss"] = critic_loss
        metrics["Grads/world_model"] = optax.global_norm(wm_grads)
        metrics["Grads/actor"] = optax.global_norm(actor_grads)
        metrics["Grads/critic"] = optax.global_norm(critic_grads)
        metrics = pmean(metrics, axis)
        if learn_on:
            # grads are already pmean'd above, so every shard computes the
            # same probe scalars — no extra collectives for the learn plane
            metrics.update(
                learn_probes(
                    {
                        "world_model": wm_grads,
                        "actor": actor_grads,
                        "critic": critic_grads,
                    },
                    params={
                        "world_model": params["world_model"],
                        "actor": params["actor"],
                        "critic": params["critic"],
                    },
                    updates={
                        "world_model": wm_updates,
                        "actor": actor_updates,
                        "critic": critic_updates,
                    },
                    losses=(wm_loss, actor_loss, critic_loss),
                    clip_norms=learn_clips,
                )
            )

        new_state = {
            "params": {
                "world_model": wm_params,
                "actor": actor_params,
                "critic": critic_params,
                "target_critic": target,
            },
            "opt": {"world_model": wm_opt, "actor": actor_opt, "critic": critic_opt},
            "moments": aux["moments"],
        }
        return new_state, metrics

    def packed_play_params(state):
        from jax.flatten_util import ravel_pytree

        # the fresh acting params leave the burst as ONE flat vector so the
        # player's next dispatch marshals a single buffer (packed player fns);
        # under a sharding plan they leave replicated, so the all-gather
        # happens once per burst instead of at every acting dispatch
        return ravel_pytree(
            {"wm": state["params"]["world_model"], "actor": state["params"]["actor"]}
        )[0]

    # step + fused-burst programs (scanned per-step inputs: key, tau). The
    # burst pattern this file pioneered now lives in the shared engine: one
    # dispatch per training burst, because on a remote-attached device every
    # dispatch pays a per-call round trip that scales with the donated
    # state's leaf count (~120 ms measured for this agent pytree over the
    # tunnel).
    return build_train_burst(
        local_step,
        fabric,
        n_scanned=2,
        plan=plan,
        extra_outputs=packed_play_params,
    )


def build_optimizers_and_state(cfg, params):
    """The three labeled optimizers + the initial agent-state pytree
    (shared with bench_dreamer.py so benchmarks can't drift from the real
    train-state layout)."""
    world_tx = instantiate(
        cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients
    )
    actor_tx = instantiate(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients)
    critic_tx = instantiate(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients)
    agent_state = {
        "params": params,
        "opt": {
            "world_model": world_tx.init(params["world_model"]),
            "actor": actor_tx.init(params["actor"]),
            "critic": critic_tx.init(params["critic"]),
        },
        "moments": init_moments(),
    }
    return world_tx, actor_tx, critic_tx, agent_state


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    # These arguments cannot be changed (reference main :394-396)
    cfg.env.frame_stack = -1
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    # Environment setup — one process drives all devices (SPMD), so the vector
    # env holds num_envs × world_size environments, each fault-tolerant via
    # RestartOnException (reference main :408-423).
    n_envs = int(cfg.env.num_envs) * world_size
    # each env fault-tolerant via RestartOnException; vector backend picked
    # by env.vectorization — env.vectorization=async keeps simulator CPU burn
    # in worker processes (the shared-memory pool, howto/async_envs.md),
    # which matters doubly on a remote-attached device: the accelerator
    # client's IO threads live here and starve behind a CPU-bound env loop
    envs = make_vector_env(cfg, fabric, log_dir, restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    is_minedojo = "minedojo" in str(cfg.env.wrapper.get("_target_", "") or "").lower()
    mask_keys = (
        ("mask_action_type", "mask_craft_smelt", "mask_equip_place", "mask_destroy")
        if is_minedojo
        else ()
    )
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.cnn_keys.encoder == [] and cfg.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    if (
        len(set(cfg.cnn_keys.encoder).intersection(set(cfg.cnn_keys.decoder))) == 0
        and len(set(cfg.mlp_keys.encoder).intersection(set(cfg.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if len(set(cfg.cnn_keys.decoder) - set(cfg.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.cnn_keys.decoder))}"
        )
    if len(set(cfg.mlp_keys.decoder) - set(cfg.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.mlp_keys.decoder))}"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)
        fabric.print("Decoder CNN keys:", cfg.cnn_keys.decoder)
        fabric.print("Decoder MLP keys:", cfg.mlp_keys.decoder)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    # Agent + optimizers + train program
    root_key, build_key = jax.random.split(root_key)
    world_model, actor, critic, params = build_agent(
        cfg, actions_dim, is_continuous, observation_space, build_key
    )
    world_tx, actor_tx, critic_tx, agent_state = build_optimizers_and_state(cfg, params)

    expl_decay_steps = 0
    state = None
    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "expl_decay_steps": 0,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        expl_decay_steps = int(np.asarray(state["expl_decay_steps"]))
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    # Parameter sharding (parallel.model_axis>1): spec-assign the whole agent
    # state — optax mu/nu mirror the param shapes, so one plan covers params
    # and optimizer state — and place it model-sharded. A resumed checkpoint
    # arrives here as full host arrays, so re-planning onto a *different*
    # model_axis than it was saved under is the same code path (respec +
    # reshard on load). model_axis=1 keeps the replicated placement untouched.
    plan = fabric.shard_plan(agent_state)
    if plan is None:
        agent_state = jax.device_put(agent_state, fabric.replicated)
    else:
        agent_state = plan.place(agent_state)
    set_shard_footprint(
        measured_bytes_per_device(agent_state["params"]),
        measured_bytes_per_device(agent_state["opt"]),
        fabric.model_axis_size,
    )

    train_fn = build_train_fn(
        world_model,
        actor,
        critic,
        world_tx,
        actor_tx,
        critic_tx,
        cfg,
        fabric,
        actions_dim,
        is_continuous,
        plan=plan,
    )
    # Two acting modes: host-mirrored (player_on_host=True on an accelerator
    # mesh — CPU snapshots refreshed per burst, utils/host.py) or packed
    # device/local acting — params cross into the player jit as ONE flat
    # vector that the train burst itself emits, so a remote-attached device
    # pays one buffer-handle per dispatch instead of hundreds.
    use_packed_player = not HostParamMirror.enabled_for(fabric, cfg)
    packed_template = (
        {"wm": params["world_model"], "actor": params["actor"]}
        if use_packed_player
        else None
    )
    player_fns = build_player_fns(
        world_model, actor, cfg, actions_dim, is_continuous,
        packed_template=packed_template,
    )

    wm_mirror = HostParamMirror.from_cfg(agent_state["params"]["world_model"], fabric, cfg)
    actor_mirror = HostParamMirror.from_cfg(agent_state["params"]["actor"], fabric, cfg)
    play_wm = wm_mirror(agent_state["params"]["world_model"])
    play_actor = actor_mirror(agent_state["params"]["actor"])
    play_packed = None
    if use_packed_player:
        from jax.flatten_util import ravel_pytree

        # under a sharding plan the packed vector is forced replicated (one
        # all-gather) so the single-device player consumes it whole
        pack_fn = (
            jax.jit(lambda t: ravel_pytree(t)[0])
            if plan is None
            else jax.jit(lambda t: ravel_pytree(t)[0], out_shardings=fabric.replicated)
        )
        play_packed = pack_fn(
            {"wm": agent_state["params"]["world_model"], "actor": agent_state["params"]["actor"]}
        )

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    # Buffer: per-env sequential sub-buffers (reference main :515-523)
    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        kind="sequential",
        obs_keys=obs_keys,
        min_size=4,
        dry_run_size=4,
    )
    # TPU-first replay staging, shared with every off-policy algo
    # (data/staging.py): with buffer.device_ring=True transitions stream to
    # HBM once at collection and train bursts are gathered on device — no
    # per-gradient-step host→device pixel upload; on a multi-device mesh the
    # ring shards itself env-wise over the data axis (each device keeps a
    # private ring shard and gathers exactly the batch slice it consumes).
    # Multi-process runs (and ring off) get the double-buffered host
    # prefetch pipeline instead.
    staging = make_replay_staging(
        cfg,
        fabric,
        rb,
        sequence_length=int(cfg.per_rank_sequence_length),
        batch_sharding=fabric.sharding(None, None, fabric.data_axis),
        seed=cfg.seed,
    )
    rb = staging.rb
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    # Global counters (reference main :534-545)
    train_step = 0
    last_train = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    updates_before_training = (
        cfg.algo.train_every // policy_steps_per_update if not cfg.dry_run else 0
    )
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.checkpoint:
        learning_starts += start_step
    max_step_expl_decay = cfg.algo.actor.max_step_expl_decay // (
        cfg.algo.per_rank_gradient_steps * world_size
    ) if cfg.algo.actor.max_step_expl_decay else 0
    expl_amount = float(cfg.algo.actor.expl_amount)
    if cfg.checkpoint.resume_from:
        expl_amount = polynomial_decay(
            expl_decay_steps,
            initial=cfg.algo.actor.expl_amount,
            final=cfg.algo.actor.expl_min,
            max_decay_steps=max_step_expl_decay,
        )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update}), so "
            "the metrics will be logged at the nearest greater multiple of the "
            "policy_steps_per_update value."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # First observation (reference main :574-590)
    o = envs.reset(seed=cfg.seed)[0]
    obs = prepare_obs(o, cnn_keys, mlp_keys, n_envs)
    if os.environ.get("SHEEPRL_ACT_DUMP"):
        import pickle

        _dump_file = os.environ["SHEEPRL_ACT_DUMP"]
        if os.path.exists(_dump_file):
            # appending a second stream onto a previous run's dump would
            # silently interleave two incompatible acting traces; start fresh
            # and say so (the dump exists to be diffed against external
            # tooling — a mixed file is worse than a missing one)
            print(
                f"SHEEPRL_ACT_DUMP: {_dump_file} already exists from a "
                "previous run — truncating it; this run's acting stream "
                "starts at row 0",
                flush=True,
            )
            open(_dump_file, "wb").close()
        with open(_dump_file, "ab") as _f:
            pickle.dump(
                {"step": -1, **{k: np.asarray(obs[k]) for k in obs_keys}}, _f
            )
    step_data = {k: obs[k][None] for k in obs_keys}
    step_data["dones"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["rewards"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, n_envs, 1), np.float32)
    player_state = player_fns["init_states"](play_wm, n_envs)

    # SHEEPRL_LOOP_TRACE=1: per-phase wall-time means printed every 50
    # updates — the remote-attached-device loop is latency-dominated and the
    # TB timers can't see through async dispatch, so this is the ground truth
    # for where a slow loop actually spends its time.
    probe = LoopProbe(every=50)

    # SHEEPRL_GC_TUNE=1: move everything built so far out of GC's reach and
    # relax collection thresholds — the hot loop allocates heavily (numpy
    # views, jax array wrappers) and full collections otherwise scan a
    # steadily growing object graph.
    if os.environ.get("SHEEPRL_GC_TUNE") not in (None, "", "0"):
        import gc

        gc.collect()
        gc.freeze()
        gc.set_threshold(100000, 50, 50)

    per_rank_gradient_steps = 0
    dumped_rows = 0
    _dump_digest = None
    # SHEEPRL_ACT_GREEDY=1 (diagnostic): act with the policy MODE instead of
    # sampling — with a seeded env this makes the whole collection loop
    # deterministic and comparable bit-for-bit against external eval tooling
    act_greedy = bool(os.environ.get("SHEEPRL_ACT_GREEDY"))
    dump_path = os.environ.get("SHEEPRL_ACT_DUMP")

    # Burst acting (tier b, howto/rollout_engine.md): K env steps per device
    # dispatch, K = env.act_burst; 1 reproduces the per-step path exactly.
    # The RSSM player state rides the burst carry next to the observation
    # (and the MineDojo validity masks when present); the host callback is
    # the whole old loop body — env step, episode bookkeeping, buffer adds —
    # and applies episode resets with the same mask * fresh + (1 - mask) *
    # state arithmetic as player_fns["reset_states"], against a host copy of
    # the fresh init state refreshed once per params version (unlike
    # DV1/DV2's zeros, DV3's fresh state has a nonzero initial posterior
    # that depends on the current world-model params).
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    n_sub = len(actions_dim)
    carry0 = {
        "obs": obs,
        "player": {k: np.asarray(v) for k, v in player_state.items()},
    }
    if is_minedojo:
        carry0["masks"] = {k: np.asarray(o[k]) for k in mask_keys}
    state_box = {
        "carry": carry0,
        "policy_step": policy_step,
        "update": start_step,
        "fresh": None,
    }

    def _fresh_player():
        # host copy of init_states under the CURRENT acting params; the
        # train block clears it whenever the params version advances
        if state_box["fresh"] is None:
            fresh = (
                player_fns["init_states_packed"](play_packed, n_envs)
                if use_packed_player
                else player_fns["init_states"](play_wm, n_envs)
            )
            state_box["fresh"] = {k: np.asarray(v) for k, v in fresh.items()}
        return state_box["fresh"]

    def _host_step_core(actions, real_actions, player_np, key_data=None):
        nonlocal dumped_rows, _dump_digest
        cur_update = state_box["update"]
        state_box["update"] += 1
        state_box["policy_step"] += n_envs
        probe.lap("act")
        step_data["actions"] = actions.reshape(1, n_envs, -1).astype(np.float32)
        rb.add(step_data)
        probe.lap("rb_add")
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            o, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated).astype(np.float32)
        probe.lap("env_step")

        step_data["is_first"] = np.zeros_like(step_data["dones"])
        if "restart_on_exception" in infos:
            for i, env_roe in enumerate(infos["restart_on_exception"]):
                if env_roe and not dones[i]:
                    # both the host copy and (when the ring is on) the HBM
                    # mirror are patched by the staging facade
                    staging.force_done_last(i)
                    step_data["is_first"][0, i] = 1.0

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        # Save the real next observation: on autoreset steps the terminal
        # observation lives in final_obs (reference main :663-668)
        next_obs_np = {k: np.asarray(o[k]) for k in o}
        dones_idxes = np.nonzero(dones.reshape(-1))[0].tolist()
        real_next_obs = {k: v.copy() for k, v in next_obs_np.items()}
        if "final_obs" in infos and len(dones_idxes) > 0:
            for idx in dones_idxes:
                fo = infos["final_obs"][idx]
                if fo is not None:
                    for k in real_next_obs:
                        if k in fo:
                            real_next_obs[k][idx] = np.asarray(fo[k])

        new_obs = prepare_obs(next_obs_np, cnn_keys, mlp_keys, n_envs)
        for k in obs_keys:
            step_data[k] = new_obs[k][None]

        rewards = np.asarray(rewards, np.float32).reshape(n_envs, 1)
        step_data["dones"] = dones.reshape(1, n_envs, 1)
        step_data["rewards"] = clip_rewards_fn(rewards)[None]

        # SHEEPRL_ACT_DUMP=<path>: append (o_{t+1}, action_t, reward_t,
        # done_t) rows for the first 1000 POLICY-acting steps — ground truth
        # for comparing the in-loop acting stream against external eval
        # tooling (random-prefill steps bind no act_key and are not dumped;
        # the window counts dumped rows, not loop iterations, so fresh runs
        # with a long prefill still capture their first 1000 policy steps)
        acted_with_policy = (
            cur_update > learning_starts or cfg.checkpoint.resume_from is not None
        )
        if dump_path and acted_with_policy and key_data is not None and dumped_rows < 1000:
            import pickle

            dumped_rows += 1
            if _dump_digest is None and play_packed is not None:
                # device->host pull of the full packed param vector: once per
                # params version, NOT per step (play_packed changes only on
                # train bursts, which reset the cache)
                _dump_digest = float(np.abs(np.asarray(play_packed)).sum())
            with open(dump_path, "ab") as _f:
                pickle.dump(
                    {
                        "step": cur_update,
                        "actions": np.asarray(actions),
                        "act_key": np.asarray(key_data),
                        "rewards": rewards.copy(),
                        "dones": dones.copy(),
                        "rec_norm": float(np.linalg.norm(player_np["recurrent"])),
                        "packed_digest": _dump_digest,
                        **{k: np.asarray(new_obs[k]) for k in obs_keys},
                    },
                    _f,
                )

        if len(dones_idxes) > 0:
            reset_obs = prepare_obs(
                {k: real_next_obs[k][dones_idxes] for k in real_next_obs},
                cnn_keys,
                mlp_keys,
                len(dones_idxes),
            )
            reset_data = {k: reset_obs[k][None] for k in obs_keys}
            reset_data["dones"] = np.ones((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["dones"])
            rb.add(reset_data, dones_idxes)

            # Reset already-inserted step data (reference main :708-712)
            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["dones"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            reset_mask = np.zeros((n_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            # same arithmetic as player_fns["reset_states"], applied
            # host-side against the cached fresh init state
            fresh = _fresh_player()
            keep = np.float32(1.0) - reset_mask
            player_np = {
                k: reset_mask * fresh[k] + keep * v for k, v in player_np.items()
            }

        carry = {"obs": new_obs, "player": player_np}
        if is_minedojo:
            carry["masks"] = {k: np.asarray(o[k]) for k in mask_keys}
        state_box["carry"] = carry
        probe.lap("bookkeeping")
        return carry

    def _host_env_step(*args):
        actions_j = [np.asarray(a) for a in args[:n_sub]]
        player_np = {
            "actions": np.asarray(args[n_sub]),
            "recurrent": np.asarray(args[n_sub + 1]),
            "stochastic": np.asarray(args[n_sub + 2]),
        }
        key_data = np.asarray(args[n_sub + 3])
        actions = np.concatenate(actions_j, -1)
        if is_continuous:
            real_actions = actions
        else:
            real_actions = np.stack([np.argmax(a, axis=-1) for a in actions_j], axis=-1)
        return _host_step_core(actions, real_actions, player_np, key_data)

    def _act_fn(p, carry, key):
        # the key advances inside the jitted burst with the same split order
        # the per-step loop used (carried key first, act key second), so the
        # K=1 key stream is bitwise the per-step stream
        key, act_key = jax.random.split(key)
        masks = carry["masks"] if is_minedojo else None
        player = carry["player"]
        # raw-obs variants: uint8 pixels cross the host→device link and are
        # normalized inside the jit; packed variants take all acting params
        # as the ONE flat vector the train burst emits
        if act_greedy:
            if use_packed_player:
                actions_j, new_player = player_fns["greedy_action_packed"](
                    p["packed"], player, carry["obs"], act_key, masks=masks
                )
            else:
                actions_j, new_player = player_fns["greedy_action_raw"](
                    p["wm"], p["actor"], player, carry["obs"], act_key, masks=masks
                )
        elif use_packed_player:
            actions_j, new_player = player_fns["exploration_action_packed"](
                p["packed"], player, carry["obs"], act_key, p["expl"], masks=masks
            )
        else:
            actions_j, new_player = player_fns["exploration_action_raw"](
                p["wm"], p["actor"], player, carry["obs"], act_key, p["expl"], masks=masks
            )
        cb_args = tuple(actions_j) + (
            new_player["actions"],
            new_player["recurrent"],
            new_player["stochastic"],
            jax.random.key_data(act_key),
        )
        return cb_args, key

    burst_actor = BurstActor(_act_fn, _host_env_step, state_box["carry"])

    # in-run eval (howto/evaluation.md): rank 0 publishes the frozen params
    # through the policy channel every eval.every_n_steps; a separate process
    # scores them, so nothing below touches the train-step critical path
    from sheeprl_tpu.evals.inrun import maybe_start_inrun_eval

    inrun = maybe_start_inrun_eval(fabric, cfg, log_dir)

    update = start_step
    while update <= num_updates:
        n_act, random_phase = train_gated_burst_plan(
            update,
            act_burst,
            learning_starts,
            num_updates,
            updates_before_training,
            resuming=cfg.checkpoint.resume_from is not None,
        )
        probe.mark()
        if random_phase:
            real_actions = actions = np.array(envs.action_space.sample())
            if not is_continuous:
                actions = np.concatenate(
                    [
                        np.eye(act_dim, dtype=np.float32)[act]
                        for act, act_dim in zip(
                            actions.reshape(len(actions_dim), -1), actions_dim
                        )
                    ],
                    axis=-1,
                )
            _host_step_core(actions, real_actions, state_box["carry"]["player"])
        else:
            burst_params = (
                {"packed": play_packed, "expl": jnp.float32(expl_amount)}
                if use_packed_player
                else {"wm": play_wm, "actor": play_actor, "expl": jnp.float32(expl_amount)}
            )
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, root_key = burst_actor.rollout(
                    burst_params, state_box["carry"], root_key, n_act
                )
            # the burst program commits its inputs to the player's device;
            # pull the carried key back to host numpy (uncommitted) so the
            # possibly multi-device train program keeps accepting it
            root_key = np.asarray(root_key)
        policy_step = state_box["policy_step"]

        update += n_act
        last = update - 1
        updates_before_training -= n_act

        # Train the agent (reference main :719-765)
        if last >= learning_starts and updates_before_training <= 0:
            n_samples = (
                cfg.algo.per_rank_pretrain_steps
                if last == learning_starts
                else cfg.algo.per_rank_gradient_steps
            )
            metrics = None
            if n_samples > 0:
                local_data = staging.sample_device(
                    cfg.per_rank_batch_size * world_size,
                    sequence_length=cfg.per_rank_sequence_length,
                    n_samples=n_samples,
                )
                probe.lap("sample")
                fetch_metrics = metric_fetch_gate(
                    cfg,
                    aggregator,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    update=last,
                    num_updates=num_updates,
                    policy_steps_per_update=policy_steps_per_update,
                    world_size=world_size,
                )
                # EMA targets: soft tau on the cadence, the run's very first
                # gradient step hard-copies
                taus = tau_schedule(
                    n_samples,
                    per_rank_gradient_steps,
                    cfg.algo.critic.target_network_update_freq,
                    tau=cfg.algo.critic.tau,
                    first_hard=True,
                )
                # NOTE: when the metric fetch is skipped, nothing in this block
                # waits on the device — the burst dispatch is async, so the
                # timer records dispatch time and the device compute overlaps
                # the next acting phase (that overlap is the point on a remote-
                # attached chip). Time/sps_train is only device-accurate on
                # bursts that fetch.
                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    root_key, train_key = jax.random.split(root_key)
                    agent_state, metrics, extras = run_train_burst(
                        train_fn,
                        agent_state,
                        local_data,
                        (jax.random.split(train_key, n_samples), jnp.asarray(taus)),
                        world_size=world_size,
                        fetch_metrics=fetch_metrics,
                        probe=probe,
                    )
                    per_rank_gradient_steps += n_samples
                    if use_packed_player:
                        play_packed = extras[0]
                        _dump_digest = None
                    else:
                        play_wm = wm_mirror(agent_state["params"]["world_model"])
                        play_actor = actor_mirror(agent_state["params"]["actor"])
                    # the cached fresh player state (episode resets) belongs
                    # to the previous params version
                    state_box["fresh"] = None
                    train_step += world_size
            updates_before_training = cfg.algo.train_every // policy_steps_per_update
            if cfg.algo.actor.expl_decay:
                expl_decay_steps += 1
                expl_amount = polynomial_decay(
                    expl_decay_steps,
                    initial=cfg.algo.actor.expl_amount,
                    final=cfg.algo.actor.expl_min,
                    max_decay_steps=max_step_expl_decay,
                )
            if aggregator and not aggregator.disabled:
                if metrics is not None:
                    for k, v in metrics.items():
                        if k in aggregator:
                            aggregator.update(k, float(np.asarray(v)))
                if "Params/exploration_amount" in aggregator:
                    aggregator.update("Params/exploration_amount", expl_amount)

        if inrun is not None and last >= learning_starts and inrun.due(policy_step):
            # versioned by policy_step; the npz write runs on the publisher's
            # writer thread, so the cost here is one params-sized device_get
            inrun.maybe_publish(
                policy_step,
                {"agent": {"params": jax.device_get(agent_state["params"])}},
            )

        # Log metrics (reference main :768-800)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        probe.tick(last)

        # Checkpoint (reference main :803-830)
        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "expl_decay_steps": expl_decay_steps,
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            with span("Time/checkpoint_time", phase="checkpoint"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
                    sharding_meta=plan.describe() if plan is not None else None,
                )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    if inrun is not None:
        inrun.close()
    staging.close()
    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(player_fns, jax.device_get(agent_state["params"]), fabric, cfg, log_dir, sample_actions=True)
