"""A2C losses (upstream sheeprl ``algos/a2c/loss.py``), pure jnp: a plain
advantage-weighted policy gradient (no ratio clipping) and an MSE value loss
(PPO's value loss with clipping off)."""

from __future__ import annotations

import jax.numpy as jnp

from sheeprl_tpu.algos.ppo.loss import _reduce
from sheeprl_tpu.algos.ppo.loss import value_loss as _ppo_value_loss


def policy_loss(
    logprobs: jnp.ndarray, advantages: jnp.ndarray, reduction: str = "mean"
) -> jnp.ndarray:
    return _reduce(-(advantages * logprobs), reduction)


def value_loss(values: jnp.ndarray, returns: jnp.ndarray, reduction: str = "mean") -> jnp.ndarray:
    return _ppo_value_loss(values, values, returns, 0.0, clip_vloss=False, reduction=reduction)
