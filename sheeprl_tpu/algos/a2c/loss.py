"""A2C losses (upstream sheeprl ``algos/a2c/loss.py``), pure jnp: a plain
advantage-weighted policy gradient (no ratio clipping) and an MSE value
loss."""

from __future__ import annotations

import jax.numpy as jnp


def _reduce(x: jnp.ndarray, reduction: str) -> jnp.ndarray:
    reduction = reduction.lower()
    if reduction == "none":
        return x
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    raise ValueError(f"Unrecognized reduction: {reduction}")


def policy_loss(
    logprobs: jnp.ndarray, advantages: jnp.ndarray, reduction: str = "mean"
) -> jnp.ndarray:
    return _reduce(-(advantages * logprobs), reduction)


def value_loss(values: jnp.ndarray, returns: jnp.ndarray, reduction: str = "mean") -> jnp.ndarray:
    return _reduce((values - returns) ** 2, reduction)
