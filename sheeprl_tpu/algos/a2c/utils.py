"""A2C helpers — same metric surface and greedy test as PPO."""

from __future__ import annotations

from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
}
