"""A2C evaluation entrypoint (upstream sheeprl ``algos/a2c/evaluate.py``):
the agent is PPO's, so the PPO eval-policy builder (registered for ``a2c``
in ``algos/ppo/evaluate.py``) serves it through the shared service."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.evals.service import run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["a2c"])
def evaluate_a2c(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
