"""A2C evaluation entrypoint (upstream sheeprl ``algos/a2c/evaluate.py``)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.utils import test
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_evaluation(algorithms=["a2c"])
def evaluate_a2c(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))

    env = make_eval_env(cfg, log_dir)
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    env.close()

    agent = build_agent(
        cfg, actions_dim, is_continuous, list(cfg.cnn_keys.encoder), list(cfg.mlp_keys.encoder)
    )
    params = params_on_device(state["params"])
    test(agent, params, fabric, cfg, log_dir)
