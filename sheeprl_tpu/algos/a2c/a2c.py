"""A2C — synchronous advantage actor-critic on the PPO chassis.

Behavioral contract from the upstream sheeprl ``algos/a2c`` (the package
snapshot mounted at /root/reference predates it — only its tests reference
``exp=a2c``, tests/test_algos/test_algos.py:146-161): PPO's rollout/GAE
machinery with the *unclipped* policy gradient ``-(A · log π)`` and an MSE
value loss, one optimization pass per rollout.

TPU-native design: identical to ``ppo/ppo.py`` — one ``shard_map``-ped jit
per update (minibatch scan, ``pmean`` grads), rollout data sharded env-major
over the mesh.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.ppo.agent import (
    PPOAgent,
    build_agent,
    evaluate_actions,
    sample_actions,
)
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    count_h2d,
    learn_probes,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, gae, normalize_tensor, save_configs
from sheeprl_tpu.utils.jax_compat import shard_map


def build_update_fn(
    agent: PPOAgent,
    tx: optax.GradientTransformation,
    cfg,
    fabric,
    n_local: int,
):
    """One SPMD program: minibatch scan with the A2C losses."""
    bs = min(int(cfg.per_rank_batch_size), n_local)
    n_mb = n_local // bs
    if n_local % bs != 0:
        warnings.warn(
            f"per_rank_batch_size ({bs}) does not divide the per-device sample count "
            f"({n_local}); the {n_local % bs} samples at the shuffle tail are dropped"
        )
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    obs_keys = tuple(cfg.mlp_keys.encoder) + cnn_keys
    reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    ent_coef = float(cfg.algo.ent_coef)
    norm_adv = bool(cfg.algo.normalize_advantages)
    axis = fabric.data_axis
    # learning-health probes (obs/learn): build-time gate, zero ops when off
    learn_on = probes_enabled(cfg)
    learn_clips = {"agent": clip_norm_of(tx)}

    def loss_fn(params, batch):
        obs = normalize_obs(batch, cnn_keys, obs_keys)
        pre_dist, new_values = agent.apply({"params": params}, obs)
        adv = batch["advantages"]
        if norm_adv:
            adv = normalize_tensor(adv)
        new_logprobs, entropy = evaluate_actions(
            pre_dist, batch["actions"], agent.actions_dim, agent.is_continuous
        )
        pg_loss = policy_loss(new_logprobs, adv, reduction)
        v_loss = value_loss(new_values, batch["returns"], reduction)
        loss = pg_loss + vf_coef * v_loss - ent_coef * entropy.mean()
        return loss, jnp.stack([pg_loss, v_loss])

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_update(params, opt_state, data, key):
        rank = jax.lax.axis_index(axis)
        perm = jax.random.permutation(jax.random.fold_in(key, rank), n_local)
        mb_idx = perm[: n_mb * bs].reshape(n_mb, bs)

        def mb_step(carry, idx):
            params, opt_state = carry
            batch = jax.tree_util.tree_map(lambda x: x[idx], data)
            (_, metrics), grads = grad_fn(params, batch)
            grads = pmean(grads, axis)
            updates, opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if learn_on:
                probes = learn_probes(
                    {"agent": grads},
                    params={"agent": params},
                    updates={"agent": updates},
                    losses=metrics,
                    clip_norms=learn_clips,
                )
                return (new_params, opt_state), (metrics, probes)
            return (new_params, opt_state), metrics

        (params, opt_state), ys = jax.lax.scan(mb_step, (params, opt_state), mb_idx)
        metrics, probes = ys if learn_on else (ys, None)
        metrics = pmean(jnp.mean(metrics, axis=0), axis)
        if learn_on:
            return params, opt_state, metrics, probes
        return params, opt_state, metrics

    shmapped = shard_map(
        local_update,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(axis), P()),
        out_specs=(P(), P(), P()) + ((P(),) if learn_on else ()),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    envs = make_vector_env(cfg, fabric, log_dir)
    observation_space = envs.single_observation_space

    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (
            envs.single_action_space.nvec.tolist()
            if is_multidiscrete
            else [envs.single_action_space.n]
        )
    )

    agent = build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys)

    root_key, init_key = jax.random.split(root_key)
    dummy_obs = {}
    for k in obs_keys:
        shape = observation_space[k].shape
        if k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape[:-2])), *shape[-2:]), jnp.float32)
        else:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape))), jnp.float32)
    params = agent.init(init_key, dummy_obs)["params"]

    tx = instantiate(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm or None)
    opt_state = tx.init(params)

    if cfg.checkpoint.resume_from:
        template = {
            "params": params,
            "opt_state": opt_state,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        params = state["params"]
        opt_state = state["opt_state"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    params = jax.device_put(params, fabric.replicated)
    opt_state = jax.device_put(opt_state, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    to_host = HostParamMirror.from_cfg(params, fabric, cfg)

    rollout_steps = int(cfg.algo.rollout_steps)
    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=obs_keys,
        size=int(cfg.buffer.size),
        min_size=rollout_steps,
        sampled=False,
    )

    def _act_fn(params, obs, key):
        # the key advances INSIDE the jitted burst (one dispatch per
        # env.act_burst env steps); the body is the old per-step
        # policy_step_fn verbatim, so act_burst=1 reproduces it bitwise
        key, sub = jax.random.split(key)
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        pre_dist, values = agent.apply({"params": params}, norm)
        actions, real_actions, _logprob = sample_actions(pre_dist, is_continuous, sub)
        return (actions, real_actions, values), key

    @jax.jit
    def value_fn(params, obs):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        return agent.apply({"params": params}, norm, method=agent.get_value)

    gamma, gae_lambda = float(cfg.algo.gamma), float(cfg.algo.gae_lambda)

    @jax.jit
    def gae_fn(rewards, values, dones, next_values):
        return gae(rewards, values, dones, next_values, gamma, gae_lambda)

    n_local = rollout_steps * int(cfg.env.num_envs)
    update_fn = build_update_fn(agent, tx, cfg, fabric, n_local)

    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = (
        int(np.asarray(state["update"])) * cfg.env.num_envs * rollout_steps
        if state is not None
        else 0
    )
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs * rollout_steps)
    num_updates = int(cfg.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    obs = envs.reset(seed=cfg.seed)[0]
    next_obs = prepare_obs(obs, cnn_keys, n_envs)
    play_params = to_host(params)
    root_key, play_key = jax.random.split(root_key)
    play_key = to_host.put_key(play_key)

    # Burst acting (envs/rollout, howto/rollout_engine.md): the acting loop
    # body below is the old per-step block moved into a host callback; the
    # BurstActor scans it env.act_burst times per device dispatch.
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    state_box = {"obs": next_obs, "policy_step": policy_step}
    #: (ring row, truncated env ids, prepared final obs) per truncation —
    #: the V(s') bootstrap is patched into the stored rewards after the
    #: burst returns (the jitted burst cannot re-enter the device)
    trunc_events = []

    def _host_env_step(actions, real_actions, values):
        state_box["policy_step"] += n_envs
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            real_actions = np.asarray(real_actions)
            obs, rewards, terminated, truncated, info = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )

        truncated_envs = np.nonzero(truncated)[0]
        if len(truncated_envs) > 0:
            # bootstrap V(s') into the reward on truncation, deferred to the
            # end of the burst
            final_obs = info["final_obs"]
            t_obs = {
                k: np.stack([np.asarray(final_obs[te][k]) for te in truncated_envs])
                for k in obs_keys
            }
            t_obs = prepare_obs(t_obs, cnn_keys, len(truncated_envs))
            trunc_events.append((int(rb._pos), truncated_envs, t_obs))

        dones = np.logical_or(terminated, truncated).astype(np.float32)
        rewards = np.asarray(rewards, dtype=np.float32)

        step_data = {
            **{k: np.asarray(state_box["obs"][k])[None] for k in obs_keys},
            "dones": dones.reshape(1, n_envs, 1),
            "values": np.asarray(values).reshape(1, n_envs, 1),
            "actions": np.asarray(actions).reshape(1, n_envs, -1),
            "rewards": rewards.reshape(1, n_envs, 1),
        }
        rb.add(step_data)

        state_box["obs"] = prepare_obs(obs, cnn_keys, n_envs)

        if cfg.metric.log_level > 0 and "final_info" in info:
            fi = info["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )
        return state_box["obs"]

    burst_actor = BurstActor(_act_fn, _host_env_step, next_obs)

    for update in range(start_step, num_updates + 1):
        remaining = rollout_steps
        while remaining > 0:
            n_act = min(act_burst, remaining)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, play_key = burst_actor.rollout(
                    play_params, state_box["obs"], play_key, n_act
                )
            remaining -= n_act
        policy_step = state_box["policy_step"]

        # patch the deferred V(s') truncation bootstraps into the stored
        # rewards (play_params were frozen for the whole rollout, so the
        # values match what the per-step path computed inline)
        for row, tr_envs, t_obs in trunc_events:
            vals = np.asarray(value_fn(play_params, t_obs)).reshape(-1)
            rewards_buf = rb["rewards"]
            rewards_buf[row, tr_envs, 0] = rewards_buf[row, tr_envs, 0] + vals
        trunc_events.clear()
        next_obs = state_box["obs"]

        next_values = value_fn(play_params, next_obs)
        returns, advantages = gae_fn(
            np.asarray(rb["rewards"]), np.asarray(rb["values"]), np.asarray(rb["dones"]), next_values
        )

        def flat(x):
            x = jnp.asarray(x)
            return jnp.swapaxes(x, 0, 1).reshape((n_envs * x.shape[0],) + x.shape[2:])

        local_np = {
            **{k: rb[k] for k in obs_keys},
            "actions": rb["actions"],
            "returns": returns,
            "advantages": advantages,
        }
        with span("Time/stage_h2d_time", phase="stage_h2d"):
            local_data = jax.device_put(
                {k: flat(v) for k, v in local_np.items()}, fabric.data_sharding
            )
        count_h2d(local_np)

        with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
            root_key, update_key = jax.random.split(root_key)
            outs = update_fn(params, opt_state, local_data, update_key)
            params, opt_state, losses = outs[0], outs[1], outs[2]
            observe_probes(outs[3] if len(outs) > 3 else None, step=policy_step)
            losses = fetch_losses_if_observed(losses, aggregator)
        play_params = to_host(params)
        train_step += world_size

        if aggregator and not aggregator.disabled:
            aggregator.update("Loss/policy_loss", losses[0])
            aggregator.update("Loss/value_loss", losses[1])

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, update, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "params": jax.device_get(params),
                "opt_state": jax.device_get(opt_state),
                "update": update * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(agent, jax.device_get(params), fabric, cfg, log_dir)
