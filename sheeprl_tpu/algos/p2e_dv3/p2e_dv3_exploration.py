"""Plan2Explore-DV3, exploration phase.

Behavioral contract from the reference
``sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py`` (train :45-560, main
:563-1125): DV3 world-model learning, plus

- **ensemble learning** (:246-271): every member regresses the *next*
  stochastic state from ``(posterior, recurrent, action)`` with an MSE
  objective;
- **exploration behaviour** (:276-421): imagination with the exploration
  actor; per-critic rewards — ``intrinsic`` = ensemble-disagreement
  (variance over members of the predicted next state, :318-333) ×
  ``intrinsic_reward_multiplier``, ``task`` = the world-model reward head —
  each with its own two-hot critic, EMA target, and Moments normalizer;
  the actor objective sums the per-critic normalized advantages weighted by
  ``weight / Σweights`` (:306-350);
- **task behaviour** (:426-540): the plain DV3 actor-critic update so the
  task policy is ready for finetuning.

TPU-native design: ONE fused ``shard_map``-ped jit per gradient step covering
all six updates (world model, ensembles, exploration actor, N exploration
critics, task actor, task critic); the ensemble runs as a single vmapped
apply (see ``agent.py``); batch dim sharded over the mesh with ``pmean``
grads; Moments all-gather per critic.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    WorldModel,
    actor_entropy,
    build_actor_dists,
    resolve_actor_distribution,
    sample_actor_actions,
)
from sheeprl_tpu.algos.dreamer_v3.loss import continue_distribution, reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    compute_lambda_values,
    init_moments,
    normalize_obs_jnp,
    prepare_obs,
    test,
    update_moments,
)
from sheeprl_tpu.algos.p2e_dv3.agent import apply_ensemble, build_agent, build_player_fns
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.distributions import MSEDistribution, SymlogDistribution, TwoHotEncodingDistribution
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.plane import train_gated_burst_plan
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import learn_probes, log_sps_metrics, probes_enabled, profile_tick, span
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.train import build_train_burst, metric_fetch_gate, run_train_burst, tau_schedule
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs

sg = jax.lax.stop_gradient


def build_train_fn(
    world_model: WorldModel,
    actor: Actor,
    critic,
    ensemble_member,
    txs: Dict[str, optax.GradientTransformation],
    cfg,
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
):
    """One fused SPMD gradient step for the exploration phase.

    ``train_step(agent_state, data, key, tau) -> (agent_state, metrics)``.
    """
    axis = fabric.data_axis
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    mlp_keys = tuple(cfg.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.mlp_keys.decoder)
    learn_on = probes_enabled(cfg)
    learn_clips = {name: clip_norm_of(tx) for name, tx in txs.items()}
    wm_cfg = cfg.algo.world_model
    stoch_flat = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    ent_coef = float(cfg.algo.actor.ent_coef)
    intrinsic_mult = float(cfg.algo.intrinsic_reward_multiplier)
    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)
    unimix = float(cfg.algo.unimix)
    moments_cfg = cfg.algo.actor.moments
    m_args = (
        float(moments_cfg.decay),
        float(moments_cfg.max),
        float(moments_cfg.percentile.low),
        float(moments_cfg.percentile.high),
    )
    dims = tuple(int(d) for d in actions_dim)
    splits = list(np.cumsum(dims)[:-1])
    critics_cfg = {
        k: {"weight": float(v["weight"]), "reward_type": str(v["reward_type"])}
        for k, v in cfg.algo.critics_exploration.items()
    }
    weights_sum = sum(c["weight"] for c in critics_cfg.values())

    def wm_apply(params, method, *args):
        return world_model.apply({"params": params}, *args, method=method)

    # -- world model loss: identical to DV3 (reference train :121-245) -----

    S, D = int(wm_cfg.stochastic_size), int(wm_cfg.discrete_size)

    def wm_loss_fn(wm_params, data, key):
        T, B = data["rewards"].shape[:2]
        batch_obs = {k: data[k] / 255.0 for k in cnn_keys}
        batch_obs.update({k: data[k] for k in mlp_keys})
        is_first = data["is_first"].at[0].set(1.0)
        batch_actions = jnp.concatenate(
            [jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], axis=0
        )
        embedded = wm_apply(wm_params, WorldModel.encode, batch_obs)
        # hoist the non-sequential work out of the time scan (same
        # optimization as dreamer_v3.py wm_loss_fn): embed projection and
        # prior logits are batched over [T, B]; the is_first reset posterior
        # is the constant prior mode at a zeroed recurrent state
        embed_proj = wm_apply(wm_params, WorldModel.project_embed, embedded)
        init_post = wm_apply(
            wm_params, WorldModel.initial_posterior, jnp.zeros((1, rec_size))
        )

        def step(carry, inp):
            posterior, recurrent = carry
            action, eproj, first, g = inp
            recurrent, posterior, post_logits = world_model.apply(
                {"params": wm_params},
                posterior, recurrent, action, eproj, first, init_post, None, g,
                method=WorldModel.dynamic_posterior,
            )
            return (posterior, recurrent), (recurrent, posterior, post_logits)

        # posterior sampling noise for the whole sequence drawn in one call
        gumbels = jax.random.gumbel(key, (T, B, S, D))
        (_, _), (recurrents, posteriors, post_logits) = jax.lax.scan(
            step,
            (jnp.zeros((B, stoch_flat)), jnp.zeros((B, rec_size))),
            (batch_actions, embed_proj, is_first, gumbels),
        )
        prior_logits = wm_apply(wm_params, WorldModel.prior_logits, recurrents)
        latents = jnp.concatenate([posteriors, recurrents], -1)
        recon = wm_apply(wm_params, WorldModel.decode, latents)
        po = {k: MSEDistribution(recon[k], dims=3) for k in cnn_dec_keys}
        po.update({k: SymlogDistribution(recon[k], dims=1) for k in mlp_dec_keys})
        pr = TwoHotEncodingDistribution(
            wm_apply(wm_params, WorldModel.reward_logits, latents), dims=1
        )
        pc = continue_distribution(wm_apply(wm_params, WorldModel.continue_logits, latents))
        loss, metrics = reconstruction_loss(
            po, batch_obs, pr, data["rewards"],
            prior_logits.reshape(T, B, S, D), post_logits.reshape(T, B, S, D),
            float(wm_cfg.kl_dynamic), float(wm_cfg.kl_representation),
            float(wm_cfg.kl_free_nats), float(wm_cfg.kl_regularizer),
            pc, 1.0 - data["dones"], float(wm_cfg.continue_scale_factor),
        )
        return loss, (metrics, sg(posteriors), sg(recurrents))

    # -- ensemble loss (reference train :246-271) --------------------------

    def ensemble_loss_fn(ens_params, posteriors, recurrents, actions):
        inp = jnp.concatenate([posteriors, recurrents, actions], -1)
        out = apply_ensemble(ensemble_member, ens_params, inp)[:, :-1]
        target = posteriors[1:][None]
        dist = MSEDistribution(out, dims=1)
        return -jnp.sum(jnp.mean(dist.log_prob(target), axis=tuple(range(1, out.ndim - 1))))

    # -- imagination with a given actor (reference :276-303 / :426-455) ----

    def imagination_rollout(wm_params, actor_params, posteriors, recurrents, key):
        prior = posteriors.reshape(-1, stoch_flat)
        recurrent = recurrents.reshape(-1, rec_size)
        latent0 = jnp.concatenate([prior, recurrent], -1)

        def policy(latent, k):
            pre = actor.apply({"params": actor_params}, sg(latent))
            dists = build_actor_dists(pre, is_continuous, distribution, init_std, min_std, unimix)
            return jnp.concatenate(sample_actor_actions(dists, is_continuous, k, True), -1)

        k0, key = jax.random.split(key)
        a0 = policy(latent0, k0)

        def step(carry, inp):
            prior, recurrent, action = carry
            g_img, k_act = inp
            prior, recurrent = world_model.apply(
                {"params": wm_params}, prior, recurrent, action, None, g_img,
                method=WorldModel.imagination,
            )
            latent = jnp.concatenate([prior, recurrent], -1)
            action = policy(latent, k_act)
            return (prior, recurrent, action), (latent, action)

        # prior-sampling noise for the whole horizon drawn in one call
        k_gum, key = jax.random.split(key)
        gumbels = jax.random.gumbel(k_gum, (horizon, prior.shape[0], S, D))
        keys = jax.random.split(key, horizon)
        _, (latents, acts) = jax.lax.scan(step, (prior, recurrent, a0), (gumbels, keys))
        return (
            jnp.concatenate([latent0[None], latents], 0),
            jnp.concatenate([a0[None], acts], 0),
        )

    def _discrete_objective(policies, imagined_actions, advantage):
        per_head = [
            p.log_prob(sg(a))[..., None][:-1]
            for p, a in zip(policies, jnp.split(imagined_actions, splits, axis=-1))
        ]
        return sum(per_head) * sg(advantage)

    # -- exploration actor loss (reference :276-395) ------------------------

    def actor_expl_loss_fn(actor_params, wm_params, ens_params, critics_params,
                           posteriors, recurrents, true_continue, moments_expl, key):
        traj, imagined_actions = imagination_rollout(
            wm_params, actor_params, posteriors, recurrents, key
        )
        continues = continue_distribution(
            wm_apply(wm_params, WorldModel.continue_logits, traj)
        ).base.mode
        continues = jnp.concatenate([true_continue[None], continues[1:]], 0)
        discount = sg(jnp.cumprod(continues * gamma, axis=0) / gamma)

        # intrinsic reward: variance over members of the predicted next state
        ens_in = jnp.concatenate([sg(traj), sg(imagined_actions)], -1)
        next_state_pred = apply_ensemble(ensemble_member, ens_params, ens_in)
        intrinsic_reward = (
            jnp.var(next_state_pred, axis=0).mean(-1, keepdims=True) * intrinsic_mult
        )

        advantage = 0.0
        new_moments = {}
        aux_critic = {}
        metrics = {}
        for k, ccfg in critics_cfg.items():
            values = TwoHotEncodingDistribution(
                critic.apply({"params": critics_params[k]["module"]}, traj), dims=1
            ).mean
            if ccfg["reward_type"] == "intrinsic":
                reward = intrinsic_reward
                metrics[f"Rewards/intrinsic_{k}"] = jnp.mean(sg(reward))
            else:
                reward = TwoHotEncodingDistribution(
                    wm_apply(wm_params, WorldModel.reward_logits, traj), dims=1
                ).mean
            lambda_values = compute_lambda_values(
                reward[1:], values[1:], continues[1:] * gamma, lmbda
            )
            nm, offset, invscale = update_moments(
                moments_expl[k], lambda_values, *m_args, axis_name=axis
            )
            new_moments[k] = nm
            advantage = advantage + (
                (lambda_values - offset) / invscale - (values[:-1] - offset) / invscale
            ) * (ccfg["weight"] / weights_sum)
            aux_critic[k] = {"lambda_values": sg(lambda_values)}
            metrics[f"Values_exploration/predicted_values_{k}"] = jnp.mean(sg(values))
            metrics[f"Values_exploration/lambda_values_{k}"] = jnp.mean(sg(lambda_values))

        pre = actor.apply({"params": actor_params}, sg(traj))
        policies = build_actor_dists(pre, is_continuous, distribution, init_std, min_std, unimix)
        if is_continuous:
            objective = advantage
        else:
            objective = _discrete_objective(policies, imagined_actions, advantage)
        entropy = ent_coef * actor_entropy(policies, distribution)
        policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[..., None][:-1]))
        aux = {
            "trajectories": sg(traj),
            "discount": discount,
            "critics": aux_critic,
            "moments": new_moments,
            "metrics": metrics,
            "Loss/policy_loss_exploration": policy_loss,
        }
        return policy_loss, aux

    # -- task actor loss: plain DV3 (reference :426-521) ---------------------

    def actor_task_loss_fn(actor_params, wm_params, critic_params, posteriors, recurrents,
                           true_continue, moments_task, key):
        traj, imagined_actions = imagination_rollout(
            wm_params, actor_params, posteriors, recurrents, key
        )
        values = TwoHotEncodingDistribution(
            critic.apply({"params": critic_params}, traj), dims=1
        ).mean
        rewards = TwoHotEncodingDistribution(
            wm_apply(wm_params, WorldModel.reward_logits, traj), dims=1
        ).mean
        continues = continue_distribution(
            wm_apply(wm_params, WorldModel.continue_logits, traj)
        ).base.mode
        continues = jnp.concatenate([true_continue[None], continues[1:]], 0)

        lambda_values = compute_lambda_values(
            rewards[1:], values[1:], continues[1:] * gamma, lmbda
        )
        discount = sg(jnp.cumprod(continues * gamma, axis=0) / gamma)
        new_moments, offset, invscale = update_moments(
            moments_task, lambda_values, *m_args, axis_name=axis
        )
        advantage = (lambda_values - offset) / invscale - (values[:-1] - offset) / invscale

        pre = actor.apply({"params": actor_params}, sg(traj))
        policies = build_actor_dists(pre, is_continuous, distribution, init_std, min_std, unimix)
        if is_continuous:
            objective = advantage
        else:
            objective = _discrete_objective(policies, imagined_actions, advantage)
        entropy = ent_coef * actor_entropy(policies, distribution)
        policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[..., None][:-1]))
        aux = {
            "trajectories": sg(traj),
            "lambda_values": sg(lambda_values),
            "discount": discount,
            "moments": new_moments,
            "Loss/policy_loss_task": policy_loss,
        }
        return policy_loss, aux

    # -- two-hot critic loss with EMA-target regularizer (reference :396-560)

    def critic_loss_fn(critic_params, target_params, traj, lambda_values, discount):
        qv = TwoHotEncodingDistribution(
            critic.apply({"params": critic_params}, traj[:-1]), dims=1
        )
        target_values = TwoHotEncodingDistribution(
            critic.apply({"params": target_params}, traj[:-1]), dims=1
        ).mean
        value_loss = -qv.log_prob(lambda_values) - qv.log_prob(sg(target_values))
        return jnp.mean(value_loss * discount[:-1, ..., 0])

    # ----------------------------------------------------------------------

    def local_step(agent_state, data, key, tau):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        params = agent_state["params"]
        opt = agent_state["opt"]
        ema = lambda c, t: jax.tree_util.tree_map(  # noqa: E731
            lambda a, b: tau * a + (1.0 - tau) * b, c, t
        )

        target_task = ema(params["critic_task"], params["target_critic_task"])
        targets_expl = {
            k: ema(params["critics_exploration"][k]["module"], params["critics_exploration"][k]["target"])
            for k in critics_cfg
        }

        k_wm, k_expl, k_task = jax.random.split(key, 3)

        # 1. world model
        (wm_loss, (wm_metrics, posteriors, recurrents)), wm_grads = jax.value_and_grad(
            wm_loss_fn, has_aux=True
        )(params["world_model"], data, k_wm)
        wm_grads = pmean(wm_grads, axis)
        wm_updates, wm_opt = txs["world_model"].update(
            wm_grads, opt["world_model"], params["world_model"]
        )
        wm_params = optax.apply_updates(params["world_model"], wm_updates)

        # 2. ensembles (actions unshifted: action[t] leads out of state t)
        ens_loss, ens_grads = jax.value_and_grad(ensemble_loss_fn)(
            params["ensembles"], posteriors, recurrents, data["actions"]
        )
        ens_grads = pmean(ens_grads, axis)
        ens_updates, ens_opt = txs["ensembles"].update(
            ens_grads, opt["ensembles"], params["ensembles"]
        )
        ens_params = optax.apply_updates(params["ensembles"], ens_updates)

        true_continue = (1.0 - data["dones"]).reshape(-1, 1)

        # 3. exploration actor
        (pl_expl, aux_expl), a_expl_grads = jax.value_and_grad(
            actor_expl_loss_fn, has_aux=True
        )(
            params["actor_exploration"], wm_params, ens_params,
            params["critics_exploration"], posteriors, recurrents,
            true_continue, agent_state["moments"]["exploration"], k_expl,
        )
        a_expl_grads = pmean(a_expl_grads, axis)
        a_expl_updates, a_expl_opt = txs["actor_exploration"].update(
            a_expl_grads, opt["actor_exploration"], params["actor_exploration"]
        )
        actor_expl_params = optax.apply_updates(params["actor_exploration"], a_expl_updates)

        # 4. exploration critics
        new_critics_expl = {}
        critics_expl_opt = {}
        critic_metrics = {}
        critics_expl_grads = {}
        critics_expl_updates = {}
        critics_expl_losses = []
        for k in critics_cfg:
            c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
                params["critics_exploration"][k]["module"],
                targets_expl[k],
                aux_expl["trajectories"],
                aux_expl["critics"][k]["lambda_values"],
                aux_expl["discount"],
            )
            c_grads = pmean(c_grads, axis)
            c_updates, c_opt = txs["critics_exploration"].update(
                c_grads, opt["critics_exploration"][k],
                params["critics_exploration"][k]["module"],
            )
            new_critics_expl[k] = {
                "module": optax.apply_updates(params["critics_exploration"][k]["module"], c_updates),
                "target": targets_expl[k],
            }
            critics_expl_opt[k] = c_opt
            critic_metrics[f"Loss/value_loss_exploration_{k}"] = c_loss
            critics_expl_grads[k] = c_grads
            critics_expl_updates[k] = c_updates
            critics_expl_losses.append(c_loss)

        # 5. task actor
        (pl_task, aux_task), a_task_grads = jax.value_and_grad(
            actor_task_loss_fn, has_aux=True
        )(
            params["actor_task"], wm_params, params["critic_task"],
            posteriors, recurrents, true_continue,
            agent_state["moments"]["task"], k_task,
        )
        a_task_grads = pmean(a_task_grads, axis)
        a_task_updates, a_task_opt = txs["actor_task"].update(
            a_task_grads, opt["actor_task"], params["actor_task"]
        )
        actor_task_params = optax.apply_updates(params["actor_task"], a_task_updates)

        # 6. task critic
        ct_loss, ct_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic_task"], target_task,
            aux_task["trajectories"], aux_task["lambda_values"], aux_task["discount"],
        )
        ct_grads = pmean(ct_grads, axis)
        ct_updates, ct_opt = txs["critic_task"].update(
            ct_grads, opt["critic_task"], params["critic_task"]
        )
        critic_task_params = optax.apply_updates(params["critic_task"], ct_updates)

        metrics = dict(wm_metrics)
        metrics.update(aux_expl["metrics"])
        metrics.update(critic_metrics)
        metrics["Loss/ensemble_loss"] = ens_loss
        metrics["Loss/policy_loss_exploration"] = pl_expl
        metrics["Loss/policy_loss_task"] = pl_task
        metrics["Loss/value_loss_task"] = ct_loss
        metrics["Grads/world_model"] = optax.global_norm(wm_grads)
        metrics["Grads/ensemble"] = optax.global_norm(ens_grads)
        metrics["Grads/actor_exploration"] = optax.global_norm(a_expl_grads)
        metrics["Grads/actor_task"] = optax.global_norm(a_task_grads)
        metrics["Grads/critic_task"] = optax.global_norm(ct_grads)
        metrics = pmean(metrics, axis)
        if learn_on:
            # grads are already pmean'd, so the probe scalars are identical
            # on every shard — the learn plane adds no collectives; the per-k
            # exploration critics fold into ONE module (dict of per-k grads)
            metrics.update(
                learn_probes(
                    {
                        "world_model": wm_grads,
                        "ensembles": ens_grads,
                        "actor_exploration": a_expl_grads,
                        "critics_exploration": critics_expl_grads,
                        "actor_task": a_task_grads,
                        "critic_task": ct_grads,
                    },
                    params={
                        "world_model": params["world_model"],
                        "ensembles": params["ensembles"],
                        "actor_exploration": params["actor_exploration"],
                        "critics_exploration": {
                            k: params["critics_exploration"][k]["module"] for k in critics_cfg
                        },
                        "actor_task": params["actor_task"],
                        "critic_task": params["critic_task"],
                    },
                    updates={
                        "world_model": wm_updates,
                        "ensembles": ens_updates,
                        "actor_exploration": a_expl_updates,
                        "critics_exploration": critics_expl_updates,
                        "actor_task": a_task_updates,
                        "critic_task": ct_updates,
                    },
                    losses=(wm_loss, ens_loss, pl_expl, pl_task, ct_loss, *critics_expl_losses),
                    clip_norms=learn_clips,
                )
            )

        new_state = {
            "params": {
                "world_model": wm_params,
                "actor_task": actor_task_params,
                "critic_task": critic_task_params,
                "target_critic_task": target_task,
                "actor_exploration": actor_expl_params,
                "critics_exploration": new_critics_expl,
                "ensembles": ens_params,
            },
            "opt": {
                "world_model": wm_opt,
                "ensembles": ens_opt,
                "actor_task": a_task_opt,
                "critic_task": ct_opt,
                "actor_exploration": a_expl_opt,
                "critics_exploration": critics_expl_opt,
            },
            "moments": {"task": aux_task["moments"], "exploration": aux_expl["moments"]},
        }
        return new_state, metrics

    # step + fused-burst programs (scanned per-step inputs: key, tau); the
    # ensemble params/optimizer state ride the burst carry with the rest
    return build_train_burst(local_step, fabric, n_scanned=2)


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    # The exploration phase always acts with the exploration actor
    # (reference main :570)
    cfg.algo.player.actor_type = "exploration"
    cfg.env.frame_stack = -1
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # each env fault-tolerant via RestartOnException; vector backend
    # picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir, restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.cnn_keys.encoder == [] and cfg.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    root_key, build_key = jax.random.split(root_key)
    world_model, actor, critic, ensemble_member, params = build_agent(
        cfg, actions_dim, is_continuous, observation_space, build_key
    )
    txs = {
        "world_model": instantiate(
            cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients
        ),
        "ensembles": instantiate(
            cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients
        ),
        "actor_task": instantiate(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": instantiate(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": instantiate(
            cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients
        ),
        "critics_exploration": instantiate(
            cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients
        ),
    }
    agent_state = {
        "params": params,
        "opt": {
            "world_model": txs["world_model"].init(params["world_model"]),
            "ensembles": txs["ensembles"].init(params["ensembles"]),
            "actor_task": txs["actor_task"].init(params["actor_task"]),
            "critic_task": txs["critic_task"].init(params["critic_task"]),
            "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
            "critics_exploration": {
                k: txs["critics_exploration"].init(params["critics_exploration"][k]["module"])
                for k in params["critics_exploration"]
            },
        },
        "moments": {
            "task": init_moments(),
            "exploration": {k: init_moments() for k in params["critics_exploration"]},
        },
    }

    expl_decay_steps = 0
    state = None
    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "expl_decay_steps": 0,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        expl_decay_steps = int(np.asarray(state["expl_decay_steps"]))
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(agent_state, fabric.replicated)

    train_fn = build_train_fn(
        world_model, actor, critic, ensemble_member, txs, cfg, fabric, actions_dim, is_continuous
    )
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)

    # host-mirrored acting snapshots (utils/host.py)
    wm_mirror = HostParamMirror.from_cfg(agent_state["params"]["world_model"], fabric, cfg)
    actor_expl_mirror = HostParamMirror.from_cfg(
        agent_state["params"]["actor_exploration"], fabric, cfg
    )
    actor_task_mirror = HostParamMirror.from_cfg(
        agent_state["params"]["actor_task"], fabric, cfg
    )
    play_wm = wm_mirror(agent_state["params"]["world_model"])
    play_actor_expl = actor_expl_mirror(agent_state["params"]["actor_exploration"])
    play_actor_task = actor_task_mirror(agent_state["params"]["actor_task"])

    def player_actor_params():
        if cfg.algo.player.actor_type == "exploration":
            return play_actor_expl
        return play_actor_task

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        kind="sequential",
        obs_keys=obs_keys,
        min_size=4,
        dry_run_size=4,
    )
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    updates_before_training = (
        cfg.algo.train_every // policy_steps_per_update if not cfg.dry_run else 0
    )
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.checkpoint:
        learning_starts += start_step
    max_step_expl_decay = cfg.algo.actor.max_step_expl_decay // (
        cfg.algo.per_rank_gradient_steps * world_size
    ) if cfg.algo.actor.max_step_expl_decay else 0
    expl_amount = float(cfg.algo.actor.expl_amount)
    if cfg.checkpoint.resume_from:
        expl_amount = polynomial_decay(
            expl_decay_steps,
            initial=cfg.algo.actor.expl_amount,
            final=cfg.algo.actor.expl_min,
            max_decay_steps=max_step_expl_decay,
        )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True, double-buffered host prefetch otherwise; the
    # whole [n, L, B, ...] burst arrives on device in one step, and the
    # per-gradient-step loop below slices device arrays (no H2D per step)
    staging = make_replay_staging(
        cfg,
        fabric,
        rb,
        sequence_length=int(cfg.per_rank_sequence_length),
        batch_sharding=fabric.sharding(None, None, fabric.data_axis),
        seed=cfg.seed,
    )
    rb = staging.rb

    o = envs.reset(seed=cfg.seed)[0]
    obs = prepare_obs(o, cnn_keys, mlp_keys, n_envs)
    step_data = {k: obs[k][None] for k in obs_keys}
    step_data["dones"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["rewards"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, n_envs, 1), np.float32)
    player_state = player_fns["init_states"](play_wm, n_envs)

    per_rank_gradient_steps = 0

    # Burst acting (tier b, howto/rollout_engine.md): K env steps per device
    # dispatch, K = env.act_burst; 1 reproduces the per-step path exactly.
    # The RSSM player state rides the burst carry next to the observation;
    # the host callback is the whole old loop body and applies episode
    # resets with the same mask * fresh + (1 - mask) * state arithmetic as
    # player_fns["reset_states"], against a host copy of the fresh init
    # state refreshed once per params version (DV3's fresh state has a
    # nonzero, params-dependent initial posterior).
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    n_sub = len(actions_dim)
    state_box = {
        "carry": {
            "obs": obs,
            "player": {k: np.asarray(v) for k, v in player_state.items()},
        },
        "policy_step": policy_step,
        "fresh": None,
    }

    def _fresh_player():
        if state_box["fresh"] is None:
            fresh = player_fns["init_states"](play_wm, n_envs)
            state_box["fresh"] = {k: np.asarray(v) for k, v in fresh.items()}
        return state_box["fresh"]

    def _host_step_core(actions, real_actions, player_np):
        state_box["policy_step"] += n_envs
        step_data["actions"] = actions.reshape(1, n_envs, -1).astype(np.float32)
        rb.add(step_data)
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            o, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated).astype(np.float32)

        step_data["is_first"] = np.zeros_like(step_data["dones"])
        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        next_obs_np = {k: np.asarray(o[k]) for k in o}
        dones_idxes = np.nonzero(dones.reshape(-1))[0].tolist()
        real_next_obs = {k: v.copy() for k, v in next_obs_np.items()}
        if "final_obs" in infos and len(dones_idxes) > 0:
            for idx in dones_idxes:
                fo = infos["final_obs"][idx]
                if fo is not None:
                    for k in real_next_obs:
                        if k in fo:
                            real_next_obs[k][idx] = np.asarray(fo[k])

        new_obs = prepare_obs(next_obs_np, cnn_keys, mlp_keys, n_envs)
        for k in obs_keys:
            step_data[k] = new_obs[k][None]

        rewards = np.asarray(rewards, np.float32).reshape(n_envs, 1)
        step_data["dones"] = dones.reshape(1, n_envs, 1)
        step_data["rewards"] = clip_rewards_fn(rewards)[None]

        if len(dones_idxes) > 0:
            reset_obs = prepare_obs(
                {k: real_next_obs[k][dones_idxes] for k in real_next_obs},
                cnn_keys, mlp_keys, len(dones_idxes),
            )
            reset_data = {k: reset_obs[k][None] for k in obs_keys}
            reset_data["dones"] = np.ones((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["dones"])
            rb.add(reset_data, dones_idxes)

            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["dones"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            reset_mask = np.zeros((n_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            # same arithmetic as player_fns["reset_states"], applied
            # host-side against the cached fresh init state
            fresh = _fresh_player()
            keep = np.float32(1.0) - reset_mask
            player_np = {
                k: reset_mask * fresh[k] + keep * v for k, v in player_np.items()
            }

        carry = {"obs": new_obs, "player": player_np}
        state_box["carry"] = carry
        return carry

    def _host_env_step(*args):
        actions_j = [np.asarray(a) for a in args[:n_sub]]
        player_np = {
            "actions": np.asarray(args[n_sub]),
            "recurrent": np.asarray(args[n_sub + 1]),
            "stochastic": np.asarray(args[n_sub + 2]),
        }
        actions = np.concatenate(actions_j, -1)
        if is_continuous:
            real_actions = actions
        else:
            real_actions = np.stack([np.argmax(a, axis=-1) for a in actions_j], axis=-1)
        return _host_step_core(actions, real_actions, player_np)

    def _act_fn(p, carry, key):
        # the key advances inside the jitted burst with the same split order
        # the per-step loop used, so the K=1 key stream is bitwise the
        # per-step stream
        key, act_key = jax.random.split(key)
        norm_obs = normalize_obs_jnp(carry["obs"], cnn_keys)
        actions_j, new_player = player_fns["exploration_action"](
            p["wm"], p["actor"], carry["player"], norm_obs, act_key, p["expl"]
        )
        cb_args = tuple(actions_j) + (
            new_player["actions"],
            new_player["recurrent"],
            new_player["stochastic"],
        )
        return cb_args, key

    burst_actor = BurstActor(_act_fn, _host_env_step, state_box["carry"])

    # in-run eval (howto/evaluation.md): rank 0 publishes the frozen params
    # through the policy channel every eval.every_n_steps; a separate process
    # scores the task actor, so nothing below touches the train-step
    # critical path
    from sheeprl_tpu.evals.inrun import maybe_start_inrun_eval

    inrun = maybe_start_inrun_eval(fabric, cfg, log_dir)

    update = start_step
    while update <= num_updates:
        n_act, random_phase = train_gated_burst_plan(
            update,
            act_burst,
            learning_starts,
            num_updates,
            updates_before_training,
            resuming=cfg.checkpoint.resume_from is not None,
        )
        if random_phase:
            real_actions = actions = np.array(envs.action_space.sample())
            if not is_continuous:
                actions = np.concatenate(
                    [
                        np.eye(act_dim, dtype=np.float32)[act]
                        for act, act_dim in zip(
                            actions.reshape(len(actions_dim), -1), actions_dim
                        )
                    ],
                    axis=-1,
                )
            _host_step_core(actions, real_actions, state_box["carry"]["player"])
        else:
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, root_key = burst_actor.rollout(
                    {
                        "wm": play_wm,
                        "actor": player_actor_params(),
                        "expl": jnp.float32(expl_amount),
                    },
                    state_box["carry"],
                    root_key,
                    n_act,
                )
            # the burst program commits its inputs to the player's device;
            # pull the carried key back to host numpy (uncommitted) so the
            # possibly multi-device train program keeps accepting it
            root_key = np.asarray(root_key)
        policy_step = state_box["policy_step"]

        update += n_act
        last = update - 1
        updates_before_training -= n_act

        if last >= learning_starts and updates_before_training <= 0:
            n_samples = (
                cfg.algo.per_rank_pretrain_steps
                if last == learning_starts
                else cfg.algo.per_rank_gradient_steps
            )
            metrics = None
            if n_samples > 0:
                local_data = staging.sample_device(
                    cfg.per_rank_batch_size * world_size,
                    sequence_length=cfg.per_rank_sequence_length,
                    n_samples=n_samples,
                )
                # EMA target updates on the host-computed cadence (first
                # gradient step hard-copies); metrics are pulled at most
                # once per burst behind the shared gate
                taus = tau_schedule(
                    n_samples,
                    per_rank_gradient_steps,
                    cfg.algo.critic.target_network_update_freq,
                    tau=cfg.algo.critic.tau,
                    first_hard=True,
                )
                fetch_metrics = metric_fetch_gate(
                    cfg,
                    aggregator,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    update=last,
                    num_updates=num_updates,
                    policy_steps_per_update=policy_steps_per_update,
                    world_size=world_size,
                )
                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    # the whole burst (n_samples gradient steps) is ONE
                    # scanned dispatch (sheeprl_tpu/train): per-call overhead
                    # on a remote-attached device would otherwise repeat per
                    # gradient step
                    root_key, train_key = jax.random.split(root_key)
                    agent_state, metrics, _ = run_train_burst(
                        train_fn,
                        agent_state,
                        local_data,
                        (jax.random.split(train_key, n_samples), jnp.asarray(taus)),
                        world_size=world_size,
                        fetch_metrics=fetch_metrics,
                    )
                    per_rank_gradient_steps += n_samples
                    play_wm = wm_mirror(agent_state["params"]["world_model"])
                    play_actor_expl = actor_expl_mirror(agent_state["params"]["actor_exploration"])
                    play_actor_task = actor_task_mirror(agent_state["params"]["actor_task"])
                    # cached fresh player state belongs to the previous
                    # params version — recompute on next episode reset
                    state_box["fresh"] = None
                    train_step += world_size
            updates_before_training = cfg.algo.train_every // policy_steps_per_update
            if cfg.algo.actor.expl_decay:
                expl_decay_steps += 1
                expl_amount = polynomial_decay(
                    expl_decay_steps,
                    initial=cfg.algo.actor.expl_amount,
                    final=cfg.algo.actor.expl_min,
                    max_decay_steps=max_step_expl_decay,
                )
            if aggregator and not aggregator.disabled:
                if metrics is not None:
                    for k, v in metrics.items():
                        if k in aggregator:
                            aggregator.update(k, float(np.asarray(v)))
                if "Params/exploration_amount" in aggregator:
                    aggregator.update("Params/exploration_amount", expl_amount)

        if inrun is not None and last >= learning_starts and inrun.due(policy_step):
            # versioned by policy_step; the npz write runs on the publisher's
            # writer thread, so the cost here is one params-sized device_get
            inrun.maybe_publish(
                policy_step,
                {"agent": {"params": jax.device_get(agent_state["params"])}},
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "expl_decay_steps": expl_decay_steps,
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    if inrun is not None:
        inrun.close()
    staging.close()
    envs.close()
    # Final greedy test runs the *task* policy (reference main :1124)
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        final = jax.device_get(agent_state["params"])
        test(
            player_fns,
            {"world_model": final["world_model"], "actor": final["actor_task"]},
            fabric, cfg, log_dir, sample_actions=True,
        )
