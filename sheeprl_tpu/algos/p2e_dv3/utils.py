"""P2E-DV3 utilities (reference ``sheeprl/algos/p2e_dv3/utils.py``):
the metric allow-list covering both phases, including the per-critic
exploration keys for the default ``intrinsic``/``extrinsic`` critics."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import AGGREGATOR_KEYS as _DV3_KEYS

AGGREGATOR_KEYS = _DV3_KEYS | {
    "Loss/ensemble_loss",
    "Loss/policy_loss_exploration",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Grads/ensemble",
    "Grads/actor_exploration",
    "Grads/actor_task",
    "Grads/critic_task",
    "Rewards/intrinsic_intrinsic",
    "Values_exploration/predicted_values_intrinsic",
    "Values_exploration/lambda_values_intrinsic",
    "Values_exploration/predicted_values_extrinsic",
    "Values_exploration/lambda_values_extrinsic",
    "Loss/value_loss_exploration_intrinsic",
    "Loss/value_loss_exploration_extrinsic",
}
