"""Plan2Explore-DV3 agent (reference ``sheeprl/algos/p2e_dv3/agent.py``
build_agent :33-219 and the ensemble construction in
``p2e_dv3_exploration.py:654-685``).

On top of the DV3 world model / actor / critic chassis this adds:

- an **ensemble** of N MLPs predicting the next stochastic state from
  ``(posterior, recurrent, action)`` — the reference builds N separate
  ``nn.Module``s with per-member seeds and loops over them; here the N
  parameter trees are *stacked* and applied with ``jax.vmap``, so all members
  run as one batched XLA program on the MXU instead of N kernel launches;
- a **dual actor** (task / exploration) sharing the Actor module definition
  (so one jitted player program serves both by swapping param trees);
- a dict of **exploration critics** (two-hot heads) keyed by name, each with
  its own EMA target and λ-return normalizer
  (``cfg.algo.critics_exploration``, reference agent.py:104-135).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import (
    ACTOR_UNIFORM_HEADS,
    CRITIC_UNIFORM_HEADS,
    WM_UNIFORM_HEADS,
    Actor,
    MLPWithHead,
    WorldModel,
    build_player_fns,  # noqa: F401  (players are identical; actor params select task/exploration)
    hafner_initialization,
    resolve_actor_distribution,
)
from sheeprl_tpu.models import MLP

import flax.linen as nn


class EnsembleMember(nn.Module):
    """One next-state predictor: MLP trunk + linear head emitting the flat
    stochastic state (reference exploration :658-681)."""

    output_dim: int
    mlp_layers: int
    dense_units: int
    layer_norm: bool = True
    activation: Any = "silu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_eps=1e-3,
            bias=not self.layer_norm,
        )(x)
        return nn.Dense(self.output_dim, name="head")(x)


def init_ensemble(
    member: EnsembleMember, n: int, input_dim: int, key: jax.Array
) -> Dict[str, Any]:
    """Stack N per-seed member param trees along a leading axis (the
    reference's per-member ``seed=cfg.seed + i``, exploration :656-681)."""
    keys = jax.random.split(key, n)
    dummy = jnp.zeros((1, input_dim), jnp.float32)
    trees = [member.init(k, dummy)["params"] for k in keys]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def apply_ensemble(member: EnsembleMember, stacked_params: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    """``[N_members, ..., output_dim]`` — all members in one vmapped apply."""
    return jax.vmap(
        lambda p: member.apply({"params": p}, x), in_axes=0
    )(stacked_params)


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    observation_space,
    key: jax.Array,
) -> Tuple[WorldModel, Actor, MLPWithHead, EnsembleMember, Dict[str, Any]]:
    """Construct the P2E-DV3 module defs + initialized params.

    Returns ``(world_model, actor, critic, ensemble_member, params)`` with
    ``params = {world_model, actor_task, critic_task, target_critic_task,
    actor_exploration, critics_exploration: {k: {module, target}}, ensembles}``.
    """
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent as dv3_build_agent

    k_dv3, k_expl_actor, k_expl_critics, k_ens, k_ha, k_hc = jax.random.split(key, 6)
    world_model, actor, critic, dv3_params = dv3_build_agent(
        cfg, actions_dim, is_continuous, observation_space, k_dv3
    )
    wm_cfg = cfg.algo.world_model
    stoch_flat = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    latent_size = stoch_flat + rec_size
    act_dim = int(np.sum(actions_dim))

    # exploration actor: same module def, fresh params
    actor_expl_params = actor.init(k_expl_actor, jnp.zeros((1, latent_size)))["params"]
    if bool(cfg.algo.hafner_initialization):
        actor_expl_params = hafner_initialization(actor_expl_params, k_ha, ACTOR_UNIFORM_HEADS)

    # exploration critics: one two-hot head + EMA target per configured name
    critics_expl: Dict[str, Any] = {}
    names = list(cfg.algo.critics_exploration.keys())
    critic_keys = jax.random.split(k_expl_critics, max(len(names), 1))
    hafner_keys = jax.random.split(k_hc, max(len(names), 1))
    for i, name in enumerate(names):
        cp = critic.init(critic_keys[i], jnp.zeros((1, latent_size)))["params"]
        if bool(cfg.algo.hafner_initialization):
            cp = hafner_initialization(cp, hafner_keys[i], CRITIC_UNIFORM_HEADS)
        critics_expl[name] = {
            "module": cp,
            "target": jax.tree_util.tree_map(jnp.copy, cp),
        }

    ens_cfg = cfg.algo.ensembles
    ensemble_member = EnsembleMember(
        output_dim=stoch_flat,
        mlp_layers=int(ens_cfg.mlp_layers),
        dense_units=int(ens_cfg.dense_units),
        layer_norm=bool(ens_cfg.layer_norm),
        activation=ens_cfg.dense_act,
    )
    ensembles = init_ensemble(
        ensemble_member, int(ens_cfg.n), latent_size + act_dim, k_ens
    )

    params = {
        "world_model": dv3_params["world_model"],
        "actor_task": dv3_params["actor"],
        "critic_task": dv3_params["critic"],
        "target_critic_task": dv3_params["target_critic"],
        "actor_exploration": actor_expl_params,
        "critics_exploration": critics_expl,
        "ensembles": ensembles,
    }
    return world_model, actor, critic, ensemble_member, params
