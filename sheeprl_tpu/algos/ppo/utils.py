"""PPO helpers: aggregator keys, obs staging, greedy test rollout.

Reference: ``sheeprl/algos/ppo/utils.py`` (AGGREGATOR_KEYS :9, test :12-56).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.envs.vector import make_eval_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}


def normalize_obs(
    obs: Dict[str, jnp.ndarray], cnn_keys: Sequence[str], obs_keys: Sequence[str]
) -> Dict[str, jnp.ndarray]:
    """uint8 pixels → centered floats; vectors pass through (reference ppo.py:60-64)."""
    return {
        k: (obs[k].astype(jnp.float32) / 255.0 - 0.5) if k in cnn_keys else obs[k].astype(jnp.float32)
        for k in obs_keys
    }


def prepare_obs(obs: Dict[str, np.ndarray], cnn_keys: Sequence[str], num_envs: int = 1) -> Dict[str, np.ndarray]:
    """Host-side staging of a raw env observation batch: flatten any frame-stack
    dim into channels for cnn keys, float32 for mlp keys (reference ppo.py:263-268)."""
    out = {}
    for k, v in obs.items():
        v = np.asarray(v)
        if k in cnn_keys:
            out[k] = v.reshape(num_envs, -1, *v.shape[-2:])
        else:
            out[k] = v.reshape(num_envs, -1).astype(np.float32)
    return out


def test(agent, params, fabric, cfg, log_dir: str) -> None:
    """Greedy single-env evaluation episode (reference utils.py:12-56)."""
    from sheeprl_tpu.algos.ppo.agent import greedy_actions

    env = make_eval_env(cfg, log_dir)
    obs_keys = list(cfg.mlp_keys.encoder) + list(cfg.cnn_keys.encoder)
    cnn_keys = list(cfg.cnn_keys.encoder)

    @jax.jit
    def act(params, obs):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        pre_dist = agent.apply({"params": params}, norm, method=agent.pre_dist)
        return greedy_actions(pre_dist, agent.is_continuous)

    done = False
    cumulative_rew = 0.0
    o = env.reset(seed=cfg.seed)[0]
    while not done:
        obs = {k: v for k, v in prepare_obs(o, cnn_keys, 1).items() if k in obs_keys}
        real_actions = np.asarray(act(params, obs))
        o, reward, terminated, truncated, _ = env.step(
            real_actions.reshape(env.action_space.shape)
        )
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
