"""PPO, coupled — the framework's first end-to-end vertical slice.

Behavioral contract from the reference ``sheeprl/algos/ppo/ppo.py``
(train :32-105, main :108-454): on-policy rollout → GAE → epochs×minibatch
clipped-surrogate SGD, with truncation bootstrapping (:291-310), annealed
lr/clip/entropy coefficients (:425-433), metric aggregation, checkpointing,
and a final greedy test on rank 0.

TPU-native design (NOT a translation):

- **One jitted update per rollout.** The reference runs a Python loop of
  epochs × minibatches with per-minibatch ``fabric.backward`` allreduces; here
  the whole update (shuffle → minibatch scan → grad → psum → optimizer) is a
  single ``shard_map``-ped, jit-compiled program: ``lax.scan`` over epochs and
  minibatches, `optax` update inline, gradients ``pmean``-ed over the mesh's
  ``data`` axis. XLA fuses the lot; the host dispatches once per update.
- **SPMD instead of DDP ranks.** One process drives all devices. The
  reference's per-rank envs/data become per-device shards of a single
  ``[n_envs_total]`` batch (``n_envs_total = env.num_envs × world_size``), so
  the reference's step accounting (`policy_steps_per_update = num_envs ×
  rollout_steps × world_size`) holds identically.
- ``buffer.share_data`` (reference ppo.py:42-52) keeps its meaning: instead of
  per-device independent shuffles, every device sees the same global
  permutation and takes its `DistributedSampler` slice — expressed inside the
  same shard_map with the data replicated instead of sharded.
- Annealing (lr / clip / entropy) is host-side state threaded into the jitted
  step as dynamic scalars — no recompilation.
- Rollout collection goes through the burst actor (``envs/rollout``,
  ``howto/rollout_engine.md``): the per-step loop body (policy → env step →
  buffer add → episode bookkeeping) runs as a host callback scanned
  ``env.act_burst`` times per device dispatch; truncation V(s') bootstraps
  are patched into the stored rewards after the burst (the acting params
  are frozen across the rollout, so the values are identical).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.ppo.agent import PPOAgent, build_agent, evaluate_actions, sample_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.obs import (
    count_h2d,
    get_telemetry,
    learn_probes,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    register_train_cost,
    shape_specs,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import clip_norm_of, set_lr
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, gae, normalize_tensor, polynomial_decay, save_configs
from sheeprl_tpu.utils.jax_compat import shard_map


def build_update_fn(
    agent: PPOAgent,
    tx: optax.GradientTransformation,
    cfg,
    fabric,
    n_local: int,
    donate: bool = True,
):
    """Compile the full PPO update as one SPMD program.

    ``n_local``: per-device sample count (rollout_steps × env.num_envs).
    Returns ``update(params, opt_state, data, key, clip_coef, ent_coef) ->
    (params, opt_state, metrics)`` where data leaves are ``[N, ...]`` arrays
    (sharded over the mesh unless ``buffer.share_data``).
    """
    share = bool(cfg.buffer.share_data)
    world = fabric.world_size
    epochs = int(cfg.algo.update_epochs)
    bs = min(int(cfg.per_rank_batch_size), n_local)
    n_mb = n_local // bs
    if n_local % bs != 0:
        warnings.warn(
            f"per_rank_batch_size ({bs}) does not divide the per-device sample count "
            f"({n_local}); each epoch drops the {n_local % bs} samples at the tail of "
            "its shuffle (static shapes are required under jit)"
        )
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    obs_keys = tuple(cfg.mlp_keys.encoder) + cnn_keys
    reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    norm_adv = bool(cfg.algo.normalize_advantages)
    axis = fabric.data_axis
    # learning-health probes (obs/learn): build-time gate, zero ops when off
    learn_on = probes_enabled(cfg)
    learn_clips = {"agent": clip_norm_of(tx)}

    def loss_fn(params, batch, clip_coef, ent_coef):
        obs = normalize_obs(batch, cnn_keys, obs_keys)
        pre_dist, new_values = agent.apply({"params": params}, obs)
        adv = batch["advantages"]
        if norm_adv:
            adv = normalize_tensor(adv)
        new_logprobs, entropy = evaluate_actions(
            pre_dist, batch["actions"], agent.actions_dim, agent.is_continuous
        )
        pg_loss = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
        v_loss = value_loss(
            new_values, batch["values"], batch["returns"], clip_coef, clip_vloss, reduction
        )
        ent_loss = entropy_loss(entropy, reduction)
        loss = pg_loss + vf_coef * v_loss + ent_coef * ent_loss
        return loss, jnp.stack([pg_loss, v_loss, ent_loss])

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_update(params, opt_state, data, key, clip_coef, ent_coef):
        rank = jax.lax.axis_index(axis)
        # per-device shuffle by default; identical global permutation +
        # DistributedSampler slice under share_data
        ep_keys = jax.random.split(key if share else jax.random.fold_in(key, rank), epochs)
        data_len = n_local * world if share else n_local

        def epoch_step(carry, ep_key):
            params, opt_state = carry
            perm = jax.random.permutation(ep_key, data_len)
            if share:
                perm = jax.lax.dynamic_slice(perm, (rank * n_local,), (n_local,))
            mb_idx = perm[: n_mb * bs].reshape(n_mb, bs)

            def mb_step(carry, idx):
                params, opt_state = carry
                batch = jax.tree_util.tree_map(lambda x: x[idx], data)
                (_, metrics), grads = grad_fn(params, batch, clip_coef, ent_coef)
                grads = pmean(grads, axis)
                updates, opt_state = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                if learn_on:
                    probes = learn_probes(
                        {"agent": grads},
                        params={"agent": params},
                        updates={"agent": updates},
                        losses=metrics,
                        clip_norms=learn_clips,
                    )
                    return (new_params, opt_state), (metrics, probes)
                return (new_params, opt_state), metrics

            carry, metrics = jax.lax.scan(mb_step, (params, opt_state), mb_idx)
            return carry, metrics

        (params, opt_state), ys = jax.lax.scan(epoch_step, (params, opt_state), ep_keys)
        metrics, probes = ys if learn_on else (ys, None)
        metrics = pmean(jnp.mean(metrics, axis=(0, 1)), axis)
        if learn_on:
            # probes stacked [epochs, n_mb]: every minibatch gradient step is
            # a sentinel sample (the host ravels them in order)
            return params, opt_state, metrics, probes
        return params, opt_state, metrics

    data_spec = P() if share else P(axis)
    shmapped = shard_map(
        local_update,
        mesh=fabric.mesh,
        in_specs=(P(), P(), data_spec, P(), P(), P()),
        out_specs=(P(), P(), P()) + ((P(),) if learn_on else ()),
        check_vma=False,
    )
    # decoupled mode keeps the old params alive for the player thread, so
    # donation must be off there (donated buffers are invalidated mid-use)
    return jax.jit(shmapped, donate_argnums=(0, 1) if donate else ())


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError(
            "MineDojo is not currently supported by PPO agent, since it does not take "
            "into consideration the action masks provided by the environment. "
            "As an alternative you can use one of the Dreamers' agents."
        )

    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    # Resume state is restored against full templates once params/opt_state
    # exist (single checkpoint read); `state` carries the restored counters.
    state = None

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    # Environment setup: the reference runs `env.num_envs` per DDP rank; here
    # one process drives all devices, so the vector env holds the whole batch.
    n_envs = int(cfg.env.num_envs) * world_size
    envs = make_vector_env(cfg, fabric, log_dir)
    observation_space = envs.single_observation_space

    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (
            envs.single_action_space.nvec.tolist()
            if is_multidiscrete
            else [envs.single_action_space.n]
        )
    )

    agent = build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys)

    # Parameter init from a dummy observation batch
    root_key, init_key = jax.random.split(root_key)
    dummy_obs = {}
    for k in obs_keys:
        shape = observation_space[k].shape
        if k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape[:-2])), *shape[-2:]), jnp.float32)
        else:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape))), jnp.float32)
    params = agent.init(init_key, dummy_obs)["params"]

    tx = instantiate(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm or None)
    opt_state = tx.init(params)

    if cfg.checkpoint.resume_from:
        # restore against a full template so optax NamedTuple states come back
        # with their original structure (orbax needs the exact tree)
        template = {
            "params": params,
            "opt_state": opt_state,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        params = jax.device_put(state["params"], fabric.replicated)
        opt_state = jax.device_put(state["opt_state"], fabric.replicated)
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    else:
        params = jax.device_put(params, fabric.replicated)
        opt_state = jax.device_put(opt_state, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    if cfg.buffer.size < cfg.algo.rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({cfg.algo.rollout_steps})"
        )
    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=obs_keys,
        size=int(cfg.buffer.size),
        sampled=False,
    )

    # ------------------------------------------------------------------
    # jitted programs
    # ------------------------------------------------------------------

    # The player runs on the CPU host with a mirrored parameter snapshot
    # (one pytree transfer per update) instead of dispatching one device
    # program per env step: env interaction is latency-bound, and over a
    # remote-attached TPU every dispatch is a network round trip
    # (SURVEY §5.8 — players pinned to CPU hosts feeding the trainer mesh).
    to_host = HostParamMirror.from_cfg(params, fabric, cfg)

    def _act_fn(params, obs, key):
        # the key advances INSIDE the jitted burst: the rollout costs one
        # dispatch per env.act_burst env steps (a host-side jax.random.split
        # per step would be a second one — over a remote TPU, a second round
        # trip); the body is the old per-step policy_step_fn verbatim, so
        # act_burst=1 reproduces the per-step path bitwise
        key, sub = jax.random.split(key)
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        pre_dist, values = agent.apply({"params": params}, norm)
        actions, real_actions, logprob = sample_actions(pre_dist, is_continuous, sub)
        return (actions, real_actions, logprob, values), key

    @jax.jit
    def value_fn(params, obs):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        return agent.apply({"params": params}, norm, method=agent.get_value)

    gamma, gae_lambda = float(cfg.algo.gamma), float(cfg.algo.gae_lambda)

    @jax.jit
    def gae_fn(rewards, values, dones, next_values):
        return gae(rewards, values, dones, next_values, gamma, gae_lambda)

    n_local = int(cfg.algo.rollout_steps) * int(cfg.env.num_envs)
    update_fn = build_update_fn(agent, tx, cfg, fabric, n_local)
    data_sharding = fabric.replicated if cfg.buffer.share_data else fabric.data_sharding

    # Global counters (reference ppo.py:227-232)
    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = (
        int(np.asarray(state["update"])) * cfg.env.num_envs * cfg.algo.rollout_steps
        if state is not None
        else 0
    )
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs * cfg.algo.rollout_steps)
    num_updates = int(cfg.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update}), so "
            "the metrics will be logged at the nearest greater multiple of the "
            "policy_steps_per_update value."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # First observation
    obs = envs.reset(seed=cfg.seed)[0]
    next_obs = prepare_obs(obs, cnn_keys, n_envs)
    play_params = to_host(params)
    root_key, play_key = jax.random.split(root_key)
    play_key = to_host.put_key(play_key)

    # Burst acting (envs/rollout, howto/rollout_engine.md): the acting loop
    # body below is the old per-step block moved into a host callback; the
    # BurstActor scans it env.act_burst times per device dispatch.
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    state_box = {"obs": next_obs, "policy_step": policy_step}
    #: (ring row, truncated env ids, prepared final obs) per truncation —
    #: the V(s') bootstrap is patched into the stored rewards after the
    #: burst returns (the jitted burst cannot re-enter the device)
    trunc_events = []

    def _host_env_step(actions, real_actions, logprobs, values):
        state_box["policy_step"] += n_envs
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            real_actions = np.asarray(real_actions)
            obs, rewards, terminated, truncated, info = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )

        truncated_envs = np.nonzero(truncated)[0]
        if len(truncated_envs) > 0:
            # bootstrap V(s') into the reward on truncation (ppo.py:291-310),
            # deferred to the end of the burst
            final_obs = info["final_obs"]
            t_obs = {
                k: np.stack([np.asarray(final_obs[te][k]) for te in truncated_envs])
                for k in obs_keys
            }
            t_obs = prepare_obs(t_obs, cnn_keys, len(truncated_envs))
            trunc_events.append((int(rb._pos), truncated_envs, t_obs))

        dones = np.logical_or(terminated, truncated).astype(np.float32)
        rewards = np.asarray(rewards, dtype=np.float32)

        step_data = {
            **{k: np.asarray(state_box["obs"][k])[None] for k in obs_keys},
            "dones": dones.reshape(1, n_envs, 1),
            "values": np.asarray(values).reshape(1, n_envs, 1),
            "actions": np.asarray(actions).reshape(1, n_envs, -1),
            "logprobs": np.asarray(logprobs).reshape(1, n_envs, 1),
            "rewards": rewards.reshape(1, n_envs, 1),
        }
        rb.add(step_data)

        state_box["obs"] = prepare_obs(obs, cnn_keys, n_envs)

        if cfg.metric.log_level > 0 and "final_info" in info:
            fi = info["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )
        return state_box["obs"]

    burst_actor = BurstActor(_act_fn, _host_env_step, next_obs)

    for update in range(start_step, num_updates + 1):
        if cfg.algo.anneal_lr:
            lr = polynomial_decay(
                update - 1,
                initial=cfg.algo.optimizer.lr,
                final=0.0,
                max_decay_steps=num_updates,
                power=1.0,
            )
            opt_state = set_lr(opt_state, lr)
        else:
            lr = cfg.algo.optimizer.lr

        remaining = int(cfg.algo.rollout_steps)
        while remaining > 0:
            n_act = min(act_burst, remaining)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, play_key = burst_actor.rollout(
                    play_params, state_box["obs"], play_key, n_act
                )
            remaining -= n_act
        policy_step = state_box["policy_step"]

        # patch the deferred V(s') truncation bootstraps into the stored
        # rewards (play_params were frozen for the whole rollout, so the
        # values match what the per-step path computed inline)
        for row, tr_envs, t_obs in trunc_events:
            vals = np.asarray(value_fn(play_params, t_obs)).reshape(-1)
            rewards_buf = rb["rewards"]
            rewards_buf[row, tr_envs, 0] = rewards_buf[row, tr_envs, 0] + vals
        trunc_events.clear()
        next_obs = state_box["obs"]

        # GAE over the whole rollout (ppo.py:350-368), one fused scan on device
        next_values = value_fn(play_params, next_obs)
        returns, advantages = gae_fn(
            np.asarray(rb["rewards"]), np.asarray(rb["values"]), np.asarray(rb["dones"]), next_values
        )

        # Assemble the flat update batch: [T, n_envs, ...] → [n_envs*T, ...]
        # (env-major so device shards own whole envs), then stage to the mesh.
        def flat(x):
            x = jnp.asarray(x)
            return jnp.swapaxes(x, 0, 1).reshape((n_envs * x.shape[0],) + x.shape[2:])

        local_np = {
            **{k: rb[k] for k in obs_keys},
            "actions": rb["actions"],
            "logprobs": rb["logprobs"],
            "values": rb["values"],
            "returns": returns,
            "advantages": advantages,
        }
        with span("Time/stage_h2d_time", phase="stage_h2d"):
            local_data = jax.device_put(
                {k: flat(v) for k, v in local_np.items()}, data_sharding
            )
        count_h2d(local_np)

        telemetry = get_telemetry()
        update_specs = None
        with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
            root_key, update_key = jax.random.split(root_key)
            update_args = (
                params,
                opt_state,
                local_data,
                update_key,
                jnp.float32(cfg.algo.clip_coef),
                jnp.float32(cfg.algo.ent_coef),
            )
            if telemetry is not None and telemetry.needs_train_flops():
                # abstract specs captured pre-call: the update donates its
                # params/opt_state buffers, so the live arrays are gone after
                update_specs = shape_specs(update_args)
            outs = update_fn(*update_args)
            params, opt_state, losses = outs[0], outs[1], outs[2]
            observe_probes(outs[3] if len(outs) > 3 else None, step=policy_step)
            losses = fetch_losses_if_observed(losses, aggregator)
        if update_specs is not None:
            # per train-step UNIT (FLOPs + bytes accessed): the counter
            # advances by world_size per dispatched update program
            register_train_cost(
                telemetry, update_fn, *update_specs, world_size=world_size
            )
        play_params = to_host(params)
        train_step += world_size

        if aggregator and not aggregator.disabled:
            aggregator.update("Loss/policy_loss", losses[0])
            aggregator.update("Loss/value_loss", losses[1])
            aggregator.update("Loss/entropy_loss", losses[2])

        if cfg.metric.log_level > 0 and logger is not None:
            logger.log_metrics({"Info/learning_rate": lr}, policy_step)
            logger.log_metrics({"Info/clip_coef": cfg.algo.clip_coef}, policy_step)
            logger.log_metrics({"Info/ent_coef": cfg.algo.ent_coef}, policy_step)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        # Anneal coefficients (ppo.py:425-433)
        if cfg.algo.anneal_clip_coef:
            cfg.algo.clip_coef = polynomial_decay(
                update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            cfg.algo.ent_coef = polynomial_decay(
                update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )

        # Checkpoint (ppo.py:435-450)
        if should_checkpoint(cfg, policy_step, last_checkpoint, update, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "params": jax.device_get(params),
                "opt_state": jax.device_get(opt_state),
                "update": update * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            with span("Time/checkpoint_time", phase="checkpoint"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
                )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(agent, params, fabric, cfg, log_dir)
