"""PPO agent: flax module + pure policy-head functions.

Behavioral contract from the reference ``sheeprl/algos/ppo/agent.py``
(CNNEncoder :14-30, MLPEncoder :33-59, PPOAgent :62-197): a MultiEncoder
feature trunk shared by an actor backbone with one linear head per discrete
sub-action (or a single mean/log_std head for continuous spaces) and an MLP
critic.

TPU-native differences: the module is a pure function of ``(params, obs)``;
sampling / log-prob / entropy live in jit-friendly helper functions that take
the head outputs (``pre_dist``) so the rollout step, the train step, and the
greedy test path each jit exactly the math they need. Actions are exchanged as
one concatenated array (one-hot per discrete sub-action, raw floats for
continuous), matching the reference's buffer layout.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import MLP, NatureCNN


class PPOAgent(nn.Module):
    """Actor-critic over dict observations."""

    actions_dim: Tuple[int, ...]
    is_continuous: bool
    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    screen_size: int
    cnn_features_dim: int = 512
    mlp_features_dim: int = 64
    encoder_dense_units: int = 64
    encoder_mlp_layers: int = 2
    encoder_dense_act: str = "relu"
    encoder_layer_norm: bool = False
    actor_dense_units: int = 64
    actor_mlp_layers: int = 2
    actor_dense_act: str = "tanh"
    actor_layer_norm: bool = False
    critic_dense_units: int = 64
    critic_mlp_layers: int = 2
    critic_dense_act: str = "tanh"
    critic_layer_norm: bool = False

    def setup(self) -> None:
        if self.cnn_keys:
            self.cnn_encoder = NatureCNN(
                features_dim=self.cnn_features_dim, screen_size=self.screen_size
            )
        if self.mlp_keys:
            self.mlp_encoder = MLP(
                hidden_sizes=(self.encoder_dense_units,) * self.encoder_mlp_layers,
                output_dim=self.mlp_features_dim,
                activation=self.encoder_dense_act,
                layer_norm=self.encoder_layer_norm,
            )
        self.critic = MLP(
            hidden_sizes=(self.critic_dense_units,) * self.critic_mlp_layers,
            output_dim=1,
            activation=self.critic_dense_act,
            layer_norm=self.critic_layer_norm,
        )
        self.actor_backbone = MLP(
            hidden_sizes=(self.actor_dense_units,) * self.actor_mlp_layers,
            output_dim=None,
            activation=self.actor_dense_act,
            layer_norm=self.actor_layer_norm,
        )
        if self.is_continuous:
            # single head emitting (mean, log_std) for all continuous dims
            self.actor_heads = [nn.Dense(int(sum(self.actions_dim)) * 2)]
        else:
            self.actor_heads = [nn.Dense(int(d)) for d in self.actions_dim]

    def features(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        feats = []
        if self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-3)
            feats.append(self.cnn_encoder(x))
        if self.mlp_keys:
            x = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            feats.append(self.mlp_encoder(x))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def pre_dist(self, obs: Dict[str, jnp.ndarray]) -> List[jnp.ndarray]:
        out = self.actor_backbone(self.features(obs))
        return [head(out) for head in self.actor_heads]

    def __call__(self, obs: Dict[str, jnp.ndarray]) -> Tuple[List[jnp.ndarray], jnp.ndarray]:
        feat = self.features(obs)
        out = self.actor_backbone(feat)
        pre_dist = [head(out) for head in self.actor_heads]
        values = self.critic(feat)
        return pre_dist, values

    def get_value(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        return self.critic(self.features(obs))


# ---------------------------------------------------------------------------
# pure policy-head math (reference PPOAgent.forward :136-180, jit-friendly)
# ---------------------------------------------------------------------------


def _split_logits(pre_dist: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    return [jax.nn.log_softmax(logits, axis=-1) for logits in pre_dist]


def sample_actions(
    pre_dist: Sequence[jnp.ndarray],
    is_continuous: bool,
    key: jax.Array,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sample → ``(stored_actions, real_actions, logprob[..., 1])``.

    ``stored_actions`` is what goes in the buffer (one-hot concat / floats);
    ``real_actions`` is what the env expects (indices / floats).
    """
    if is_continuous:
        mean, log_std = jnp.split(pre_dist[0], 2, axis=-1)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape, dtype=mean.dtype)
        actions = mean + std * eps
        logprob = _normal_log_prob(actions, mean, std).sum(axis=-1, keepdims=True)
        return actions, actions, logprob
    log_probs = _split_logits(pre_dist)
    onehots, idxs, lps = [], [], []
    for i, lp in enumerate(log_probs):
        sub_key = jax.random.fold_in(key, i)
        idx = jax.random.categorical(sub_key, lp, axis=-1)
        onehot = jax.nn.one_hot(idx, lp.shape[-1], dtype=lp.dtype)
        onehots.append(onehot)
        idxs.append(idx[..., None])
        lps.append(jnp.take_along_axis(lp, idx[..., None], axis=-1))
    actions = jnp.concatenate(onehots, axis=-1)
    real_actions = jnp.concatenate(idxs, axis=-1)
    logprob = jnp.concatenate(lps, axis=-1).sum(axis=-1, keepdims=True)
    return actions, real_actions, logprob


def evaluate_actions(
    pre_dist: Sequence[jnp.ndarray],
    actions: jnp.ndarray,
    actions_dim: Sequence[int],
    is_continuous: bool,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Log-prob and entropy of stored actions → ``(logprob[...,1], entropy[...,1])``."""
    if is_continuous:
        mean, log_std = jnp.split(pre_dist[0], 2, axis=-1)
        std = jnp.exp(log_std)
        logprob = _normal_log_prob(actions, mean, std).sum(axis=-1, keepdims=True)
        entropy = (0.5 + 0.5 * jnp.log(2 * jnp.pi) + log_std).sum(axis=-1, keepdims=True)
        return logprob, entropy
    log_probs = _split_logits(pre_dist)
    splits = np.cumsum(np.asarray(actions_dim))[:-1]
    sub_actions = jnp.split(actions, splits, axis=-1)
    lps, ents = [], []
    for lp, act in zip(log_probs, sub_actions):
        lps.append(jnp.sum(act * lp, axis=-1, keepdims=True))
        probs = jnp.exp(lp)
        ents.append(-jnp.sum(probs * lp, axis=-1, keepdims=True))
    logprob = jnp.concatenate(lps, axis=-1).sum(axis=-1, keepdims=True)
    entropy = jnp.concatenate(ents, axis=-1).sum(axis=-1, keepdims=True)
    return logprob, entropy


def greedy_actions(
    pre_dist: Sequence[jnp.ndarray], is_continuous: bool
) -> jnp.ndarray:
    """Mode actions in env format (reference get_greedy_actions :185-197)."""
    if is_continuous:
        mean, _ = jnp.split(pre_dist[0], 2, axis=-1)
        return mean
    return jnp.concatenate([jnp.argmax(l, axis=-1)[..., None] for l in pre_dist], axis=-1)


def _normal_log_prob(x: jnp.ndarray, mean: jnp.ndarray, std: jnp.ndarray) -> jnp.ndarray:
    var = std**2
    return -((x - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cnn_keys: Sequence[str],
    mlp_keys: Sequence[str],
) -> PPOAgent:
    """Construct the agent from the composed config (reference build at ppo.py:178-190)."""
    enc, act, crit = cfg.algo.encoder, cfg.algo.actor, cfg.algo.critic
    return PPOAgent(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=is_continuous,
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        screen_size=cfg.env.screen_size,
        cnn_features_dim=enc.cnn_features_dim,
        mlp_features_dim=enc.mlp_features_dim,
        encoder_dense_units=enc.dense_units,
        encoder_mlp_layers=enc.mlp_layers,
        encoder_dense_act=enc.dense_act,
        encoder_layer_norm=enc.layer_norm,
        actor_dense_units=act.dense_units,
        actor_mlp_layers=act.mlp_layers,
        actor_dense_act=act.dense_act,
        actor_layer_norm=act.layer_norm,
        critic_dense_units=crit.dense_units,
        critic_mlp_layers=crit.mlp_layers,
        critic_dense_act=crit.dense_act,
        critic_layer_norm=crit.layer_norm,
    )
