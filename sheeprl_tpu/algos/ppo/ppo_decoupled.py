"""PPO, decoupled — player/trainer split.

Behavioral contract from the reference ``sheeprl/algos/ppo/ppo_decoupled.py``
(main :597-644, player :33-346, trainer :349-594): one process dedicated to
environment interaction and the rest to optimization, exchanging rollout
chunks and updated parameters once per update, with the player always acting
with the last broadcast parameters.

TPU-native design: the reference's three Gloo/NCCL process groups
(cfg broadcast, ``scatter_object_list`` rollout chunks, flat-param broadcast,
``Join`` for uneven chunks — :619-640) collapse into a **player thread on
the CPU host** feeding the SPMD trainer mesh through a depth-1 queue:

- the player thread steps the envs and runs the jitted policy on the current
  parameter snapshot while the main thread runs the update program on the
  *previous* rollout (double buffering — env interaction and TPU compute
  overlap instead of alternating);
- parameter "broadcast" is swapping one replicated pytree reference; rollout
  "scatter" is one sharded ``device_put`` (even chunking by construction, so
  no Join semantics are needed);
- the stored behavior-policy log-probs make the one-rollout parameter
  staleness exact for the clipped objective.

Requires ≥2 devices like the reference (registry ``decoupled=True``; the
CLI enforces it, cli.py check_configs).
"""

from __future__ import annotations

import os
import queue
import threading
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent, sample_actions
from sheeprl_tpu.algos.ppo.ppo import build_update_fn
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.obs import (
    add_act_dispatches,
    count_h2d,
    cost_flops_of,
    get_telemetry,
    log_sps_metrics,
    shape_specs,
    span,
)
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.optim import set_lr
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, gae, polynomial_decay, save_configs


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    envs = make_vector_env(cfg, fabric, log_dir)
    observation_space = envs.single_observation_space

    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (
            envs.single_action_space.nvec.tolist()
            if is_multidiscrete
            else [envs.single_action_space.n]
        )
    )

    agent = build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys)

    root_key, init_key = jax.random.split(root_key)
    dummy_obs = {}
    for k in obs_keys:
        shape = observation_space[k].shape
        if k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape[:-2])), *shape[-2:]), jnp.float32)
        else:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape))), jnp.float32)
    params = agent.init(init_key, dummy_obs)["params"]

    tx = instantiate(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm or None)
    opt_state = tx.init(params)

    if cfg.checkpoint.resume_from:
        template = {
            "params": params,
            "opt_state": opt_state,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        params = state["params"]
        opt_state = state["opt_state"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    params = jax.device_put(params, fabric.replicated)
    opt_state = jax.device_put(opt_state, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rollout_steps = int(cfg.algo.rollout_steps)

    @jax.jit
    def policy_step_fn(params, obs, key):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        pre_dist, values = agent.apply({"params": params}, norm)
        actions, real_actions, logprob = sample_actions(pre_dist, is_continuous, key)
        return actions, real_actions, logprob, values

    @jax.jit
    def value_fn(params, obs):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        return agent.apply({"params": params}, norm, method=agent.get_value)

    gamma, gae_lambda = float(cfg.algo.gamma), float(cfg.algo.gae_lambda)

    @jax.jit
    def gae_fn(rewards, values, dones, next_values):
        return gae(rewards, values, dones, next_values, gamma, gae_lambda)

    n_local = rollout_steps * int(cfg.env.num_envs)
    update_fn = build_update_fn(agent, tx, cfg, fabric, n_local, donate=False)
    data_sharding = fabric.replicated if cfg.buffer.share_data else fabric.data_sharding

    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_steps_per_update = int(n_envs * rollout_steps)
    num_updates = int(cfg.total_steps) // policy_steps_per_update if not cfg.dry_run else 1
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_step = (start_step - 1) * policy_steps_per_update

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # ------------------------------------------------------------------
    # the player thread (reference player(), :33-346)
    # ------------------------------------------------------------------

    # depth-1 queue = the double buffer: the player fills rollout k+1 while
    # the trainer consumes rollout k
    rollout_q: "queue.Queue[Any]" = queue.Queue(maxsize=1)
    # the "param broadcast": the trainer swaps in the new snapshot, the
    # player reads whichever is current (jax arrays are immutable, so a torn
    # read is impossible); the snapshot lives on the CPU host so the player's
    # per-step policy dispatch never leaves the host (utils/host.py)
    to_host = HostParamMirror.from_cfg(params, fabric, cfg)
    param_cell = {"params": to_host(params)}
    stop = threading.Event()
    player_error: Dict[str, BaseException] = {}

    # run-health: both sides of the decoupled pair heartbeat once per unit of
    # progress; the watchdog flags whichever wedges (hung env worker, dead
    # device link, deadlocked queue) instead of the run going silent
    telemetry = get_telemetry()
    watchdog = telemetry.watchdog() if telemetry is not None else None
    if watchdog is not None:
        watchdog.register("ppo-player")
        watchdog.register("ppo-trainer")
        watchdog.start()

    def player(player_key):
        try:
            obs = envs.reset(seed=cfg.seed)[0]
            next_obs = prepare_obs(obs, cnn_keys, n_envs)
            for update in range(start_step, num_updates + 1):
                rollout = {k: [] for k in obs_keys}
                extras = {"dones": [], "values": [], "actions": [], "logprobs": [], "rewards": []}
                ep_stats = []
                snapshot = param_cell["params"]
                with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
                    for _ in range(rollout_steps):
                        if watchdog is not None:
                            watchdog.beat("ppo-player")
                        nonlocal_key = jax.random.fold_in(player_key, len(extras["dones"]) + update * rollout_steps)
                        actions_j, real_actions_j, logprob_j, values_j = policy_step_fn(
                            snapshot, next_obs, nonlocal_key
                        )
                        add_act_dispatches(1)
                        real_actions = np.asarray(real_actions_j)
                        obs, rewards, terminated, truncated, info = envs.step(
                            real_actions.reshape(envs.action_space.shape)
                        )

                        truncated_envs = np.nonzero(truncated)[0]
                        if len(truncated_envs) > 0:
                            final_obs = info["final_obs"]
                            t_obs = {
                                k: np.stack([np.asarray(final_obs[te][k]) for te in truncated_envs])
                                for k in obs_keys
                            }
                            t_obs = prepare_obs(t_obs, cnn_keys, len(truncated_envs))
                            vals = np.asarray(value_fn(snapshot, t_obs)).reshape(-1)
                            rewards = np.asarray(rewards, dtype=np.float32)
                            rewards[truncated_envs] += vals

                        dones = np.logical_or(terminated, truncated).astype(np.float32)
                        for k in obs_keys:
                            rollout[k].append(np.asarray(next_obs[k]))
                        extras["dones"].append(dones.reshape(n_envs, 1))
                        extras["values"].append(np.asarray(values_j).reshape(n_envs, 1))
                        extras["actions"].append(np.asarray(actions_j).reshape(n_envs, -1))
                        extras["logprobs"].append(np.asarray(logprob_j).reshape(n_envs, 1))
                        extras["rewards"].append(
                            np.asarray(rewards, np.float32).reshape(n_envs, 1)
                        )
                        next_obs = prepare_obs(obs, cnn_keys, n_envs)

                        if cfg.metric.log_level > 0 and "final_info" in info:
                            fi = info["final_info"]
                            if isinstance(fi, dict) and "episode" in fi:
                                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                                for i in np.nonzero(mask)[0]:
                                    ep_stats.append(
                                        (float(fi["episode"]["r"][i]), float(fi["episode"]["l"][i]))
                                    )

                    next_values = np.asarray(value_fn(snapshot, next_obs))

                payload = {
                    "data": {
                        **{k: np.stack(rollout[k]) for k in obs_keys},
                        **{k: np.stack(v) for k, v in extras.items()},
                    },
                    "next_values": next_values,
                    "ep_stats": ep_stats,
                }
                if watchdog is not None:
                    # blocking on a full queue = waiting for the trainer, not
                    # a stall of the player
                    watchdog.pause("ppo-player")
                rollout_q.put(payload)
                if watchdog is not None:
                    watchdog.resume("ppo-player")
                if stop.is_set():
                    break
        except BaseException as e:  # surface crashes in the trainer loop
            player_error["error"] = e
            rollout_q.put(None)
        finally:
            if watchdog is not None:  # a finished player is not a stalled one
                watchdog.unregister("ppo-player")

    root_key, player_key = jax.random.split(root_key)
    player_thread = threading.Thread(target=player, args=(player_key,), daemon=True, name="ppo-player")
    player_thread.start()

    # ------------------------------------------------------------------
    # the trainer loop (reference trainer(), :349-594)
    # ------------------------------------------------------------------

    last_train = 0
    train_step = 0

    try:
        for update in range(start_step, num_updates + 1):
            if cfg.algo.anneal_lr:
                lr = polynomial_decay(
                    update - 1,
                    initial=cfg.algo.optimizer.lr,
                    final=0.0,
                    max_decay_steps=num_updates,
                    power=1.0,
                )
                opt_state = set_lr(opt_state, lr)
            else:
                lr = cfg.algo.optimizer.lr

            if watchdog is not None:
                # blocking on an empty queue = waiting for the player, not a
                # stall of the trainer
                watchdog.pause("ppo-trainer")
            payload = rollout_q.get()
            if payload is None:
                raise RuntimeError("PPO player thread crashed") from player_error.get("error")
            if watchdog is not None:
                watchdog.beat("ppo-trainer")
            policy_step += policy_steps_per_update

            returns, advantages = gae_fn(
                payload["data"]["rewards"],
                payload["data"]["values"],
                payload["data"]["dones"],
                payload["next_values"],
            )

            def flat(x):
                x = jnp.asarray(x)
                return jnp.swapaxes(x, 0, 1).reshape((n_envs * x.shape[0],) + x.shape[2:])

            with span("Time/stage_h2d_time", phase="stage_h2d"):
                local_data = {
                    **{k: flat(payload["data"][k]) for k in obs_keys},
                    "actions": flat(payload["data"]["actions"]),
                    "logprobs": flat(payload["data"]["logprobs"]),
                    "values": flat(payload["data"]["values"]),
                    "returns": flat(returns),
                    "advantages": flat(advantages),
                }
                local_data = jax.device_put(local_data, data_sharding)
            count_h2d(payload["data"])

            with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                root_key, update_key = jax.random.split(root_key)
                update_args = (
                    params,
                    opt_state,
                    local_data,
                    update_key,
                    jnp.float32(cfg.algo.clip_coef),
                    jnp.float32(cfg.algo.ent_coef),
                )
                params, opt_state, losses = update_fn(*update_args)
                losses = fetch_losses_if_observed(losses, aggregator)
            if telemetry is not None and telemetry.needs_train_flops():
                # donation is off in decoupled mode, so the live args are
                # still valid for the one-off AOT cost analysis; per
                # train-step UNIT (the counter advances by world_size per
                # dispatched update program)
                flops = cost_flops_of(update_fn, *shape_specs(update_args))
                telemetry.set_train_flops(flops / world_size if flops else None)
            train_step += world_size

            # the new parameters become visible to the player (the reference's
            # rank-1 → rank-0 flat-parameter broadcast, :525-529)
            param_cell["params"] = to_host(params)

            if cfg.metric.log_level > 0 and logger is not None:
                logger.log_metrics({"Info/learning_rate": lr}, policy_step)
                logger.log_metrics({"Info/clip_coef": cfg.algo.clip_coef}, policy_step)
                logger.log_metrics({"Info/ent_coef": cfg.algo.ent_coef}, policy_step)

            if aggregator and not aggregator.disabled:
                aggregator.update("Loss/policy_loss", losses[0])
                aggregator.update("Loss/value_loss", losses[1])
                aggregator.update("Loss/entropy_loss", losses[2])
                for ep_rew, ep_len in payload["ep_stats"]:
                    if "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or update == num_updates
            ):
                if aggregator and not aggregator.disabled:
                    metrics_dict = aggregator.compute()
                    if logger is not None:
                        logger.log_metrics(metrics_dict, policy_step)
                    aggregator.reset()
                log_sps_metrics(
                    logger,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    last_train=last_train,
                    world_size=world_size,
                    action_repeat=cfg.env.action_repeat,
                )
                last_log = policy_step
                last_train = train_step

            if cfg.algo.anneal_clip_coef:
                cfg.algo.clip_coef = polynomial_decay(
                    update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                cfg.algo.ent_coef = polynomial_decay(
                    update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )

            if should_checkpoint(cfg, policy_step, last_checkpoint, update, num_updates):
                last_checkpoint = policy_step
                ckpt_state = {
                    "params": jax.device_get(params),
                    "opt_state": jax.device_get(opt_state),
                    "update": update * world_size,
                    "batch_size": cfg.per_rank_batch_size * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(
                    log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}"
                )
                with span("Time/checkpoint_time", phase="checkpoint"):
                    fabric.call("on_checkpoint_player", ckpt_path=ckpt_path, state=ckpt_state)
                if preemption_requested():
                    # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                    # drains the in-flight write) — leave the train loop cleanly
                    break
    finally:
        stop.set()
        try:  # unblock a player waiting on the full queue
            rollout_q.get_nowait()
        except queue.Empty:
            pass
        player_thread.join(timeout=30)
        if watchdog is not None:
            watchdog.stop()

    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(agent, jax.device_get(params), fabric, cfg, log_dir)
