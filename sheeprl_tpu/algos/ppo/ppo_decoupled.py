"""PPO, decoupled — actor–learner plane.

Behavioral contract from the reference ``sheeprl/algos/ppo/ppo_decoupled.py``
(main :597-644, player :33-346, trainer :349-594): one process dedicated to
environment interaction and the rest to optimization, exchanging rollout
chunks and updated parameters once per update, with the player always acting
with the last broadcast parameters.

TPU-native design (``sheeprl_tpu/plane``, howto/actor_learner.md): this
entrypoint is the **learner**. Collection runs in the player loop
(:mod:`sheeprl_tpu.algos.ppo.player`) on the execution plane selected by
``plane.num_players``:

- ``0`` (default) — one player *thread* streaming one rollout slab per
  update over an in-memory bounded queue
  (:class:`~sheeprl_tpu.plane.supervisor.LocalPlane`);
- ``N > 0`` — N player *processes*, each owning its slice of the env fleet
  through the PR-5 async vector plane, streaming fixed-layout rollout slabs
  over shared-memory ring queues
  (:class:`~sheeprl_tpu.plane.supervisor.ProcessPlane`), hot-reloading
  policy versions published atomically through the PR-2 checkpoint writer.

The reference's three Gloo/NCCL process groups (cfg broadcast,
``scatter_object_list`` rollout chunks, flat-param broadcast, ``Join`` for
uneven chunks — :619-640) collapse into the plane's two channels: the slab
ring (even chunking by construction — each player owns a fixed env slice)
and the version-monotone policy publication. The stored behavior-policy
log-probs make the protocol's bounded parameter staleness exact for the
clipped objective. Requires ≥2 devices like the reference.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.player import ppo_slab_example, run_player
from sheeprl_tpu.algos.ppo.ppo import build_update_fn
from sheeprl_tpu.algos.ppo.utils import test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.obs import (
    count_h2d,
    get_telemetry,
    log_sps_metrics,
    observe_probes,
    profile_tick,
    register_train_cost,
    shape_specs,
    span,
)
from sheeprl_tpu.plane import (
    SlabSpec,
    build_plane,
    plane_env_split,
    version_after,
)
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.optim import set_lr
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, gae, polynomial_decay, save_configs


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # the learner never steps envs — players own them (ppo/player.py). One
    # probe env pins the wrapped spaces the whole plane agrees on.
    probe = make_eval_env(cfg, None, prefix="train")
    action_space = probe.action_space
    observation_space = probe.observation_space
    probe.close()

    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    act_width = int(np.prod(action_space.shape)) if is_continuous else int(sum(actions_dim))

    agent = build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys)

    root_key, init_key = jax.random.split(root_key)
    dummy_obs = {}
    for k in obs_keys:
        shape = observation_space[k].shape
        if k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape[:-2])), *shape[-2:]), jnp.float32)
        else:
            dummy_obs[k] = jnp.zeros((1, int(np.prod(shape))), jnp.float32)
    params = agent.init(init_key, dummy_obs)["params"]

    tx = instantiate(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm or None)
    opt_state = tx.init(params)

    if cfg.checkpoint.resume_from:
        template = {
            "params": params,
            "opt_state": opt_state,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        params = state["params"]
        opt_state = state["opt_state"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    params = jax.device_put(params, fabric.replicated)
    opt_state = jax.device_put(opt_state, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rollout_steps = int(cfg.algo.rollout_steps)

    gamma, gae_lambda = float(cfg.algo.gamma), float(cfg.algo.gae_lambda)

    @jax.jit
    def gae_fn(rewards, values, dones, next_values):
        return gae(rewards, values, dones, next_values, gamma, gae_lambda)

    n_local = rollout_steps * int(cfg.env.num_envs)
    update_fn = build_update_fn(agent, tx, cfg, fabric, n_local, donate=False)
    data_sharding = fabric.replicated if cfg.buffer.share_data else fabric.data_sharding

    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_steps_per_update = int(n_envs * rollout_steps)
    num_updates = int(cfg.total_steps) // policy_steps_per_update if not cfg.dry_run else 1
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_step = (start_step - 1) * policy_steps_per_update

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # ------------------------------------------------------------------
    # the actor–learner plane (sheeprl_tpu/plane, howto/actor_learner.md)
    # ------------------------------------------------------------------

    num_players, envs_per_player = plane_env_split(cfg, n_envs)
    slab_spec = SlabSpec.from_arrays(
        ppo_slab_example(
            rollout_steps, envs_per_player, observation_space, cnn_keys, mlp_keys, act_width
        )
    )
    scalars = {
        "num_updates": num_updates,
        "learning_starts": 0,  # PPO trains from the first update
        "first_train_update": start_step,
        "act_burst": max(int(cfg.env.get("act_burst", 1) or 1), 1),
        "max_policy_lag": int(cfg.get("plane", {}).get("max_policy_lag", 0) or 0),
    }

    # the "param broadcast": an atomic policy publication players hot-reload;
    # the snapshot lives on the CPU host (utils/host.py) so player acting
    # never leaves the host
    to_host = HostParamMirror.from_cfg(params, fabric, cfg)
    root_key, player_key = jax.random.split(root_key)
    player_keys = [player_key] + [
        jax.random.fold_in(player_key, p) for p in range(1, max(num_players, 1))
    ]

    telemetry = get_telemetry()
    watchdog = telemetry.watchdog() if telemetry is not None else None
    if watchdog is not None:
        watchdog.register("ppo-learner")
        watchdog.start()

    plane = build_plane(
        cfg,
        spec=slab_spec,
        entry="sheeprl_tpu.algos.ppo.player:run_player",
        run_player=run_player,
        scalars=scalars,
        player_keys=player_keys,
        algo_name=cfg.algo.name,
        start_update=start_step,
        n_envs=n_envs,
        log_dir=log_dir,
        player_log_dir=log_dir if fabric.is_global_zero else None,
        thread_name="ppo-player",
        initial_params=to_host(params),
        watchdog=watchdog,
    )

    # ------------------------------------------------------------------
    # the learner loop (reference trainer(), :349-594): one clipped-surrogate
    # update program per received rollout
    # ------------------------------------------------------------------

    last_train = 0
    train_step = 0

    try:
        for update in range(start_step, num_updates + 1):
            if cfg.algo.anneal_lr:
                lr = polynomial_decay(
                    update - 1,
                    initial=cfg.algo.optimizer.lr,
                    final=0.0,
                    max_decay_steps=num_updates,
                    power=1.0,
                )
                opt_state = set_lr(opt_state, lr)
            else:
                lr = cfg.algo.optimizer.lr

            if watchdog is not None:
                # waiting on player rollouts is idleness, not a stall
                watchdog.pause("ppo-learner")
            with span("Time/plane_wait_time", SumMetric(sync_on_compute=False), phase="plane_wait"):
                handles = [plane.recv(p, update) for p in range(plane.n_players)]
            if watchdog is not None:
                watchdog.beat("ppo-learner")
            policy_step += policy_steps_per_update

            if plane.n_players == 1:
                rollout = {k: v for k, v in handles[0].data.items()}
            else:
                # assemble the full-width rollout in player order — the env
                # axis concatenation restores the canonical seed order
                rollout = {
                    k: np.concatenate([h.data[k] for h in handles], axis=1)
                    for k in handles[0].data
                }
            next_values = rollout.pop("next_values")[0]
            ep_stats = [s for h in handles for s in h.ep_stats]

            returns, advantages = gae_fn(
                rollout["rewards"], rollout["values"], rollout["dones"], next_values
            )

            def flat(x):
                x = jnp.asarray(x)
                return jnp.swapaxes(x, 0, 1).reshape((n_envs * x.shape[0],) + x.shape[2:])

            with span("Time/stage_h2d_time", phase="stage_h2d"):
                local_data = {
                    **{k: flat(rollout[k]) for k in obs_keys},
                    "actions": flat(rollout["actions"]),
                    "logprobs": flat(rollout["logprobs"]),
                    "values": flat(rollout["values"]),
                    "returns": flat(returns),
                    "advantages": flat(advantages),
                }
                local_data = jax.device_put(local_data, data_sharding)
            count_h2d(rollout)
            for h in handles:
                h.release()

            with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                root_key, update_key = jax.random.split(root_key)
                update_args = (
                    params,
                    opt_state,
                    local_data,
                    update_key,
                    jnp.float32(cfg.algo.clip_coef),
                    jnp.float32(cfg.algo.ent_coef),
                )
                outs = update_fn(*update_args)
                params, opt_state, losses = outs[0], outs[1], outs[2]
                observe_probes(outs[3] if len(outs) > 3 else None, step=policy_step)
                losses = fetch_losses_if_observed(losses, aggregator)
            if telemetry is not None and telemetry.needs_train_flops():
                # donation is off in decoupled mode, so the live args are
                # still valid for the one-off AOT cost analysis; per
                # train-step UNIT (the counter advances by world_size per
                # dispatched update program)
                register_train_cost(
                    telemetry, update_fn, *shape_specs(update_args),
                    world_size=world_size,
                )
            train_step += world_size

            # the parameter broadcast (reference :525-529): an atomic policy
            # publication players hot-reload
            plane.publish(version_after(update, start_step), to_host(params))

            if cfg.metric.log_level > 0 and logger is not None:
                logger.log_metrics({"Info/learning_rate": lr}, policy_step)
                logger.log_metrics({"Info/clip_coef": cfg.algo.clip_coef}, policy_step)
                logger.log_metrics({"Info/ent_coef": cfg.algo.ent_coef}, policy_step)

            if aggregator and not aggregator.disabled:
                aggregator.update("Loss/policy_loss", losses[0])
                aggregator.update("Loss/value_loss", losses[1])
                aggregator.update("Loss/entropy_loss", losses[2])
                for ep_rew, ep_len in ep_stats:
                    if "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or update == num_updates
            ):
                if aggregator and not aggregator.disabled:
                    metrics_dict = aggregator.compute()
                    if logger is not None:
                        logger.log_metrics(metrics_dict, policy_step)
                    aggregator.reset()
                log_sps_metrics(
                    logger,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    last_train=last_train,
                    world_size=world_size,
                    action_repeat=cfg.env.action_repeat,
                )
                profile_tick(policy_step=policy_step, world_size=world_size)
                last_log = policy_step
                last_train = train_step

            if cfg.algo.anneal_clip_coef:
                cfg.algo.clip_coef = polynomial_decay(
                    update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                cfg.algo.ent_coef = polynomial_decay(
                    update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )

            if should_checkpoint(cfg, policy_step, last_checkpoint, update, num_updates):
                last_checkpoint = policy_step
                ckpt_state = {
                    "params": jax.device_get(params),
                    "opt_state": jax.device_get(opt_state),
                    "update": update * world_size,
                    "batch_size": cfg.per_rank_batch_size * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(
                    log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}"
                )
                with span("Time/checkpoint_time", phase="checkpoint"):
                    fabric.call("on_checkpoint_player", ckpt_path=ckpt_path, state=ckpt_state)
                if preemption_requested():
                    # SIGTERM/SIGINT: the final checkpoint is saved; leave the
                    # loop cleanly — plane.drain() below joins the players
                    break
    finally:
        plane.drain()
        if watchdog is not None:
            watchdog.stop()

    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(agent, jax.device_get(params), fabric, cfg, log_dir)
