"""PPO losses (reference ``sheeprl/algos/ppo/loss.py:6-72``), pure jnp."""

from __future__ import annotations

import jax.numpy as jnp


def _reduce(x: jnp.ndarray, reduction: str) -> jnp.ndarray:
    reduction = reduction.lower()
    if reduction == "none":
        return x
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    raise ValueError(f"Unrecognized reduction: {reduction}")


def policy_loss(
    new_logprobs: jnp.ndarray,
    logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    clip_coef: jnp.ndarray,
    reduction: str = "mean",
) -> jnp.ndarray:
    """Clipped surrogate objective, equation (7) of the PPO paper."""
    logratio = new_logprobs - logprobs
    ratio = jnp.exp(logratio)
    pg_loss1 = advantages * ratio
    pg_loss2 = advantages * jnp.clip(ratio, 1.0 - clip_coef, 1.0 + clip_coef)
    return _reduce(-jnp.minimum(pg_loss1, pg_loss2), reduction)


def value_loss(
    new_values: jnp.ndarray,
    old_values: jnp.ndarray,
    returns: jnp.ndarray,
    clip_coef: jnp.ndarray,
    clip_vloss: bool,
    reduction: str = "mean",
) -> jnp.ndarray:
    if not clip_vloss:
        values_pred = new_values
    else:
        values_pred = old_values + jnp.clip(new_values - old_values, -clip_coef, clip_coef)
    return _reduce((values_pred - returns) ** 2, reduction)


def entropy_loss(entropy: jnp.ndarray, reduction: str = "mean") -> jnp.ndarray:
    return _reduce(-entropy, reduction)
