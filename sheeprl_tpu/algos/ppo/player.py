"""PPO player loop for the actor–learner plane.

One function, :func:`run_player`, drives PPO collection in BOTH decoupled
modes: as a thread inside the learner process (``plane.num_players=0``, the
:class:`~sheeprl_tpu.plane.supervisor.LocalPlane` transport) and as a
spawned player process on the multi-process plane (imported by dotted name
from :mod:`sheeprl_tpu.plane.worker`). One trajectory slab = one full
rollout of ``algo.rollout_steps`` env steps for this player's env slice,
plus the burst-level extras the learner's GAE needs (``next_values``).

Unlike SAC, the PPO player needs the *whole* agent (policy and value head):
it stores behavior values/log-probs per step, bootstraps V(s') into rewards
on truncation, and closes each rollout with V(s_T) — so the publication
channel carries the full ``params`` pytree, and the frozen per-rollout
snapshot makes those values exactly what the coupled path computes inline.

Acting runs through the PR-6 :class:`~sheeprl_tpu.envs.rollout.BurstActor`
(``env.act_burst`` acts per device dispatch) with the per-step key folded
from the player key and the global env-step index *inside* the scanned
body, so trajectories are burst-size-invariant.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

__all__ = ["run_player", "ppo_slab_example"]


def ppo_slab_example(
    rollout_steps: int,
    n_envs: int,
    observation_space,
    cnn_keys: List[str],
    mlp_keys: List[str],
    act_width: int,
) -> Dict[str, np.ndarray]:
    """Example arrays fixing the PPO trajectory-slab layout: one rollout of
    ``rollout_steps`` steps for ``n_envs`` envs, per prepared obs key, plus
    the one-per-burst ``next_values`` row."""
    from sheeprl_tpu.algos.ppo.utils import prepare_obs

    raw = {
        k: np.zeros((n_envs, *observation_space[k].shape), observation_space[k].dtype)
        for k in cnn_keys + mlp_keys
    }
    prepared = prepare_obs(raw, cnn_keys, n_envs)
    example = {
        k: np.zeros((rollout_steps, *v.shape), v.dtype) for k, v in prepared.items()
    }
    example.update(
        {
            "dones": np.zeros((rollout_steps, n_envs, 1), np.float32),
            "values": np.zeros((rollout_steps, n_envs, 1), np.float32),
            "actions": np.zeros((rollout_steps, n_envs, act_width), np.float32),
            "logprobs": np.zeros((rollout_steps, n_envs, 1), np.float32),
            "rewards": np.zeros((rollout_steps, n_envs, 1), np.float32),
            "next_values": np.zeros((1, n_envs, 1), np.float32),
        }
    )
    return example


def run_player(ctx) -> None:
    """Collect updates ``[ctx.start_update, num_updates]`` for this player's
    env slice, one committed slab per rollout."""
    import jax

    from sheeprl_tpu.envs.rollout import BurstActor
    from sheeprl_tpu.envs.vector import env_seeds, make_vector_env
    from sheeprl_tpu.obs import span
    from sheeprl_tpu.utils.metric import SumMetric

    cfg = ctx.cfg
    n_envs = int(ctx.n_envs)

    if ctx.process_mode and cfg.env.get("vectorization", None) is None and cfg.env.get(
        "sync_env", None
    ) is None:
        cfg.env.vectorization = "async"
    if ctx.restart_count:
        # a respawned player must not replay the exact pre-crash trajectories
        cfg.seed = int(cfg.seed) + 7919 * int(ctx.restart_count)

    envs = make_vector_env(
        cfg,
        fabric=None,
        log_dir=ctx.log_dir if ctx.player_idx == 0 else None,
        n_envs=n_envs,
        rank=ctx.env_rank,
    )
    try:
        _player_body(
            ctx, cfg, envs, env_seeds, n_envs, jax, BurstActor, span, SumMetric
        )
    finally:
        ctx.close_watchdog()
        envs.close()


def _player_body(ctx, cfg, envs, env_seeds, n_envs, jax, BurstActor, span, SumMetric):
    import gymnasium as gym
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.agent import build_agent, sample_actions
    from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs

    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys
    rollout_steps = int(cfg.algo.rollout_steps)

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (
            envs.single_action_space.nvec.tolist()
            if is_multidiscrete
            else [envs.single_action_space.n]
        )
    )
    agent = build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys)

    @jax.jit
    def value_fn(params, obs):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        return agent.apply({"params": params}, norm, method=agent.get_value)

    o = envs.reset(seed=env_seeds(int(cfg.seed), int(ctx.env_rank), n_envs))[0]
    obs = prepare_obs(o, cnn_keys, n_envs)
    player_key = jnp.asarray(ctx.player_key)
    act_burst = ctx.act_burst

    # mutable state the host callback and the rollout loop share
    box: Dict[str, Any] = {"obs": obs, "views": None, "row": 0, "eps": [], "u": 0}
    #: (slab row, truncated env ids, prepared final obs) per truncation — the
    #: V(s') bootstrap is patched into the slab rewards after each burst (the
    #: params are frozen for the whole rollout, so the values are identical
    #: to the inline per-step computation)
    trunc_events: List[Tuple[int, np.ndarray, Dict[str, np.ndarray]]] = []

    def _host_env_step(actions, real_actions, logprobs, values):
        real_actions = np.asarray(real_actions)
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            next_o, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )

        views, r = box["views"], box["row"]
        truncated_envs = np.nonzero(truncated)[0]
        if len(truncated_envs) > 0:
            final_obs = infos["final_obs"]
            t_obs = {
                k: np.stack([np.asarray(final_obs[te][k]) for te in truncated_envs])
                for k in obs_keys
            }
            trunc_events.append(
                (r, truncated_envs, prepare_obs(t_obs, cnn_keys, len(truncated_envs)))
            )

        dones = np.logical_or(terminated, truncated).astype(np.float32)
        for k in obs_keys:
            views[k][r] = box["obs"][k]
        views["dones"][r] = dones.reshape(n_envs, 1)
        views["values"][r] = np.asarray(values).reshape(n_envs, 1)
        views["actions"][r] = np.asarray(actions, np.float32).reshape(n_envs, -1)
        views["logprobs"][r] = np.asarray(logprobs).reshape(n_envs, 1)
        views["rewards"][r] = np.asarray(rewards, np.float32).reshape(n_envs, 1)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    box["eps"].append(
                        (float(fi["episode"]["r"][i]), float(fi["episode"]["l"][i]))
                    )

        box["obs"] = prepare_obs(next_o, cnn_keys, n_envs)
        box["row"] = r + 1
        box["u"] += 1
        ctx.beat()  # a hung envs.step() must fire the stall watchdog
        return {**box["obs"], "__u": np.uint32(box["u"])}

    def _act_fn(params, carry, key):
        # per-step key = fold_in(player_key, global env-step index) INSIDE
        # the scan: burst-size-invariant trajectories
        step_key = jax.random.fold_in(key, carry["__u"])
        obs_in = {k: carry[k] for k in obs_keys}
        norm = normalize_obs(obs_in, cnn_keys, obs_keys)
        pre_dist, values = agent.apply({"params": params}, norm)
        actions, real_actions, logprob = sample_actions(pre_dist, is_continuous, step_key)
        return (actions, real_actions, logprob, values), key

    burst_actor = BurstActor(
        _act_fn, _host_env_step, {**obs, "__u": np.uint32(0)}
    )

    update = int(ctx.start_update)
    while update <= ctx.num_updates and not ctx.stop.is_set() and not ctx.orphaned():
        version, params = ctx.wait_policy(update)
        token, views = ctx.acquire_slab()
        box["views"], box["row"] = views, 0
        box["u"] = (update - 1) * rollout_steps
        ep_stats: List[Tuple[float, float]] = []
        box["eps"] = ep_stats
        trunc_events.clear()

        remaining = rollout_steps
        while remaining > 0:
            n_act = min(act_burst, remaining)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                burst_actor.rollout(
                    params, {**box["obs"], "__u": np.uint32(box["u"])}, player_key, n_act
                )
            remaining -= n_act

        # deferred truncation bootstraps + the rollout-closing V(s_T), both
        # on the frozen snapshot
        for row, tr_envs, t_obs in trunc_events:
            vals = np.asarray(value_fn(params, t_obs)).reshape(-1)
            views["rewards"][row, tr_envs, 0] = views["rewards"][row, tr_envs, 0] + vals
        views["next_values"][0] = np.asarray(value_fn(params, box["obs"])).reshape(n_envs, 1)

        ctx.emit(token, views, update, rollout_steps, version, ep_stats)
        update += 1
