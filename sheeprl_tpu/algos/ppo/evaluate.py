"""PPO evaluation entrypoint (reference ``sheeprl/algos/ppo/evaluate.py:15-66``)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.utils import test
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_evaluation(algorithms=["ppo"])
def evaluate_ppo(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))

    env = make_eval_env(cfg, log_dir)
    observation_space = env.observation_space

    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
    fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)

    is_continuous = isinstance(env.action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        env.action_space.shape
        if is_continuous
        else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
    )
    env.close()

    agent = build_agent(
        cfg, actions_dim, is_continuous, cfg.cnn_keys.encoder, cfg.mlp_keys.encoder
    )
    params = params_on_device(state["params"])
    test(agent, params, fabric, cfg, log_dir)


# Same model as coupled PPO — the checkpoint layout is identical.
@register_evaluation(algorithms=["ppo_decoupled"])
def evaluate_ppo_decoupled(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    evaluate_ppo(fabric, cfg, state)
