"""PPO evaluation (reference ``sheeprl/algos/ppo/evaluate.py:15-66``),
collapsed onto the shared eval service. ppo_decoupled and a2c train the same
agent/checkpoint layout, so one builder serves all three."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent, greedy_actions
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs
from sheeprl_tpu.evals.builders import actions_dim_of
from sheeprl_tpu.evals.service import EvalPolicy, register_eval_builder, run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_eval_builder(algorithms=["ppo", "ppo_decoupled", "a2c"])
def ppo_eval_policy(fabric, cfg, state, observation_space, action_space) -> EvalPolicy:
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    actions_dim, is_continuous = actions_dim_of(action_space)
    agent = build_agent(
        cfg, actions_dim, is_continuous, list(cfg.cnn_keys.encoder), list(cfg.mlp_keys.encoder)
    )
    params = params_on_device(state["params"])
    cnn_keys = list(cfg.cnn_keys.encoder)
    obs_keys = list(cfg.mlp_keys.encoder) + cnn_keys

    @jax.jit
    def _act(p, obs):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        pre_dist = agent.apply({"params": p}, norm, method=agent.pre_dist)
        return greedy_actions(pre_dist, agent.is_continuous)

    def act(obs, policy_state, key):
        n = int(np.asarray(next(iter(obs.values()))).shape[0])
        prepared = {k: v for k, v in prepare_obs(obs, cnn_keys, n).items() if k in obs_keys}
        return np.asarray(_act(params, prepared)), policy_state

    return EvalPolicy(act=act)


@register_evaluation(algorithms=["ppo"])
def evaluate_ppo(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)


# Same model as coupled PPO — the checkpoint layout is identical.
@register_evaluation(algorithms=["ppo_decoupled"])
def evaluate_ppo_decoupled(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
