"""DreamerV2 — discrete-latent world model with KL balancing.

Behavioral contract from the reference ``sheeprl/algos/dreamer_v2/dreamer_v2.py``
(train :43-426, main :429-870): sequence-replay world-model learning with
KL-balanced categorical state loss, 15-step imagination with the action
computed inside the rollout, reinforce/dynamics-mixed actor objective
(``objective_mix``), Gaussian critic regressed on bootstrapped TD(λ) returns,
and a hard-copied target critic every ``target_network_update_freq`` steps.

TPU-native design: identical chassis to ``dreamer_v3.py`` — one
``shard_map``-ped jit per gradient step, ``lax.scan`` over T and H,
``lax.pmean`` gradients, dynamic tau (here 0/1: hard copy) — with the V2
losses. Data layout note (reference main :572-745): row *t* of the buffer
holds the action that *led to* observation *t*, so the dynamic-learning scan
consumes ``data["actions"]`` unshifted (unlike V3).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v2.agent import (
    Actor,
    WorldModel,
    actor_entropy,
    build_actor_dists,
    build_agent,
    build_player_fns,
    resolve_actor_distribution,
    sample_actor_actions,
)
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v2.utils import (
    compute_lambda_values,
    normalize_obs_jnp,
    prepare_obs,
    test,
)
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.distributions import Bernoulli, Independent, Normal
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.plane import train_gated_burst_plan
from sheeprl_tpu.train import build_train_burst, metric_fetch_gate, run_train_burst, tau_schedule
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import learn_probes, log_sps_metrics, probes_enabled, profile_tick, span
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs

sg = jax.lax.stop_gradient


def build_train_fn(
    world_model: WorldModel,
    actor: Actor,
    critic,
    world_tx: optax.GradientTransformation,
    actor_tx: optax.GradientTransformation,
    critic_tx: optax.GradientTransformation,
    cfg,
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
):
    """Compile one full DreamerV2 gradient step as a single SPMD program.

    Returns a :class:`~sheeprl_tpu.train.TrainProgram`: callable as
    ``train_step(agent_state, data, key, tau) -> (agent_state, metrics)``
    (``tau`` is 1.0 on hard-copy steps, 0.0 otherwise), with ``.burst``
    scanning the step over a staged ``[n_samples, ...]`` block as ONE
    dispatch.
    """
    axis = fabric.data_axis
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    mlp_keys = tuple(cfg.mlp_keys.encoder)
    learn_on = probes_enabled(cfg)
    learn_clips = {
        "world_model": clip_norm_of(world_tx),
        "actor": clip_norm_of(actor_tx),
        "critic": clip_norm_of(critic_tx),
    }
    wm_cfg = cfg.algo.world_model
    stoch_flat = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    kl_balancing_alpha = float(wm_cfg.kl_balancing_alpha)
    kl_free_nats = float(wm_cfg.kl_free_nats)
    kl_free_avg = bool(wm_cfg.kl_free_avg)
    kl_regularizer = float(wm_cfg.kl_regularizer)
    discount_scale = float(wm_cfg.discount_scale_factor)
    use_continues = bool(wm_cfg.use_continues)
    ent_coef = float(cfg.algo.actor.ent_coef)
    objective_mix = float(cfg.algo.actor.objective_mix)
    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)
    dims = tuple(int(d) for d in actions_dim)
    splits = list(np.cumsum(dims)[:-1])

    S, D = int(wm_cfg.stochastic_size), int(wm_cfg.discrete_size)

    def wm_apply(params, method, *args):
        return world_model.apply({"params": params}, *args, method=method)

    # ------------------------------------------------------------------
    # world-model loss (reference train :104-240)
    # ------------------------------------------------------------------

    def wm_loss_fn(wm_params, data, key):
        T, B = data["rewards"].shape[:2]
        batch_obs = {k: data[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k] for k in mlp_keys})
        is_first = data["is_first"].at[0].set(1.0)
        embedded = wm_apply(wm_params, WorldModel.encode, batch_obs)

        def step(carry, inp):
            posterior, recurrent = carry
            action, embed, first, g = inp
            recurrent, posterior, post_logits = world_model.apply(
                {"params": wm_params},
                posterior,
                recurrent,
                action,
                embed,
                first,
                None,
                g,
                method=WorldModel.dynamic_posterior,
            )
            return (posterior, recurrent), (recurrent, posterior, post_logits)

        # posterior sampling noise for the whole sequence in one draw; the
        # prior (transition) logits never feed back into the loop and are
        # batched over [T, B] after the scan (same optimization as DV3)
        gumbels = jax.random.gumbel(key, (T, B, S, D))
        (_, _), (recurrents, posteriors, post_logits) = jax.lax.scan(
            step,
            (jnp.zeros((B, stoch_flat)), jnp.zeros((B, rec_size))),
            (data["actions"], embedded, is_first, gumbels),
        )
        prior_logits = wm_apply(wm_params, WorldModel.prior_logits, recurrents)
        latents = jnp.concatenate([posteriors, recurrents], -1)
        recon = wm_apply(wm_params, WorldModel.decode, latents)
        po = {
            k: Independent(Normal(recon[k], jnp.ones_like(recon[k])), 3 if k in cnn_keys else 1)
            for k in recon
        }
        pr = Independent(Normal(wm_apply(wm_params, WorldModel.reward, latents), 1.0), 1)
        if use_continues:
            pc = Independent(Bernoulli(logits=wm_apply(wm_params, WorldModel.continues, latents)), 1)
            continue_targets = (1.0 - data["dones"]) * gamma
        else:
            pc = continue_targets = None
        loss, metrics = reconstruction_loss(
            po,
            batch_obs,
            pr,
            data["rewards"],
            prior_logits.reshape(T, B, S, D),
            post_logits.reshape(T, B, S, D),
            kl_balancing_alpha,
            kl_free_nats,
            kl_free_avg,
            kl_regularizer,
            pc,
            continue_targets,
            discount_scale,
        )
        return loss, (metrics, sg(posteriors), sg(recurrents))

    # ------------------------------------------------------------------
    # actor loss via imagination (reference train :253-398)
    # ------------------------------------------------------------------

    def imagination_rollout(wm_params, actor_params, posteriors, recurrents, key):
        """H-step prior rollout with the action computed inside the loop
        (reference :299-320). Returns ``(trajectories [H+1, BT, L],
        actions [H+1, BT, A])`` with ``actions[0] = 0``."""
        prior = posteriors.reshape(-1, stoch_flat)
        recurrent = recurrents.reshape(-1, rec_size)
        latent0 = jnp.concatenate([prior, recurrent], -1)

        def policy(latent, k):
            pre = actor.apply({"params": actor_params}, sg(latent))
            dists = build_actor_dists(
                pre, is_continuous, distribution, init_std, min_std, unimix=0.0
            )
            return jnp.concatenate(
                sample_actor_actions(dists, is_continuous, k, True), -1
            )

        def step(carry, inp):
            prior, recurrent, latent = carry
            g_img, k_act = inp
            action = policy(latent, k_act)
            prior, recurrent = world_model.apply(
                {"params": wm_params},
                prior,
                recurrent,
                action,
                None,
                g_img,
                method=WorldModel.imagination,
            )
            latent = jnp.concatenate([prior, recurrent], -1)
            return (prior, recurrent, latent), (latent, action)

        # prior-sampling noise for the whole horizon in one draw
        k_gum, key = jax.random.split(key)
        gumbels = jax.random.gumbel(k_gum, (horizon, prior.shape[0], S, D))
        keys = jax.random.split(key, horizon)
        _, (latents, acts) = jax.lax.scan(step, (prior, recurrent, latent0), (gumbels, keys))
        trajectories = jnp.concatenate([latent0[None], latents], 0)
        actions = jnp.concatenate([jnp.zeros_like(acts[:1]), acts], 0)
        return trajectories, actions

    def actor_loss_fn(actor_params, wm_params, target_params, posteriors, recurrents,
                      true_continue, key):
        traj, imagined_actions = imagination_rollout(
            wm_params, actor_params, posteriors, recurrents, key
        )
        # values from the *target* critic (reference :322-327)
        predicted_values = critic.apply({"params": target_params}, traj)
        predicted_rewards = wm_apply(wm_params, WorldModel.reward, traj)
        if use_continues:
            continues = jax.nn.sigmoid(wm_apply(wm_params, WorldModel.continues, traj))
            continues = jnp.concatenate([true_continue[None] * gamma, continues[1:]], 0)
        else:
            continues = jnp.ones_like(sg(predicted_rewards)) * gamma

        lambda_values = compute_lambda_values(
            predicted_rewards[:-1],
            predicted_values[:-1],
            continues[:-1],
            bootstrap=predicted_values[-1:],
            lmbda=lmbda,
        )
        discount = sg(
            jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-1]], 0), 0)
        )

        pre = actor.apply({"params": actor_params}, sg(traj[:-2]))
        policies = build_actor_dists(
            pre, is_continuous, distribution, init_std, min_std, unimix=0.0
        )

        # dynamics backprop vs reinforce, mixed (reference :366-383)
        dynamics = lambda_values[1:]
        advantage = sg(lambda_values[1:] - predicted_values[:-2])
        per_head = [
            p.log_prob(sg(a[1:-1]))[..., None]
            for p, a in zip(policies, jnp.split(imagined_actions, splits, axis=-1))
        ]
        reinforce = sum(per_head) * advantage
        objective = objective_mix * reinforce + (1 - objective_mix) * dynamics
        entropy = ent_coef * actor_entropy(policies, distribution)
        policy_loss = -jnp.mean(discount[:-2] * (objective + entropy[..., None]))
        aux = {
            "trajectories": sg(traj),
            "lambda_values": sg(lambda_values),
            "discount": discount,
            "Loss/policy_loss": policy_loss,
            "User/PredictedRewards": jnp.mean(sg(predicted_rewards)),
            "User/LambdaValues": jnp.mean(sg(lambda_values)),
        }
        return policy_loss, aux

    # ------------------------------------------------------------------
    # critic loss (reference train :399-418)
    # ------------------------------------------------------------------

    def critic_loss_fn(critic_params, traj, lambda_values, discount):
        qv = Independent(Normal(critic.apply({"params": critic_params}, traj[:-1]), 1.0), 1)
        return -jnp.mean(discount[:-1, ..., 0] * qv.log_prob(lambda_values))

    # ------------------------------------------------------------------
    # the fused step
    # ------------------------------------------------------------------

    def local_step(agent_state, data, key, tau):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        params = agent_state["params"]
        opt = agent_state["opt"]

        # hard target copy on tau=1 steps (reference main :779-785)
        target = jax.tree_util.tree_map(
            lambda c, t: tau * c + (1.0 - tau) * t,
            params["critic"],
            params["target_critic"],
        )

        k_wm, k_img = jax.random.split(key)

        (wm_loss, (wm_metrics, posteriors, recurrents)), wm_grads = jax.value_and_grad(
            wm_loss_fn, has_aux=True
        )(params["world_model"], data, k_wm)
        wm_grads = pmean(wm_grads, axis)
        wm_updates, wm_opt = world_tx.update(wm_grads, opt["world_model"], params["world_model"])
        wm_params = optax.apply_updates(params["world_model"], wm_updates)

        true_continue = (1.0 - data["dones"]).reshape(-1, 1)
        (actor_loss, aux), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            params["actor"],
            wm_params,
            target,
            posteriors,
            recurrents,
            true_continue,
            k_img,
        )
        actor_grads = pmean(actor_grads, axis)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt["actor"], params["actor"])
        actor_params = optax.apply_updates(params["actor"], actor_updates)

        critic_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"],
            aux["trajectories"],
            aux["lambda_values"],
            aux["discount"],
        )
        critic_grads = pmean(critic_grads, axis)
        critic_updates, critic_opt = critic_tx.update(critic_grads, opt["critic"], params["critic"])
        critic_params = optax.apply_updates(params["critic"], critic_updates)

        metrics = dict(wm_metrics)
        metrics["Loss/policy_loss"] = aux["Loss/policy_loss"]
        metrics["User/PredictedRewards"] = aux["User/PredictedRewards"]
        metrics["User/LambdaValues"] = aux["User/LambdaValues"]
        metrics["Loss/value_loss"] = critic_loss
        metrics["Grads/world_model"] = optax.global_norm(wm_grads)
        metrics["Grads/actor"] = optax.global_norm(actor_grads)
        metrics["Grads/critic"] = optax.global_norm(critic_grads)
        metrics = pmean(metrics, axis)
        if learn_on:
            # grads are already pmean'd, so the probe scalars are identical
            # on every shard — the learn plane adds no collectives
            metrics.update(
                learn_probes(
                    {
                        "world_model": wm_grads,
                        "actor": actor_grads,
                        "critic": critic_grads,
                    },
                    params={
                        "world_model": params["world_model"],
                        "actor": params["actor"],
                        "critic": params["critic"],
                    },
                    updates={
                        "world_model": wm_updates,
                        "actor": actor_updates,
                        "critic": critic_updates,
                    },
                    losses=(wm_loss, actor_loss, critic_loss),
                    clip_norms=learn_clips,
                )
            )

        new_state = {
            "params": {
                "world_model": wm_params,
                "actor": actor_params,
                "critic": critic_params,
                "target_critic": target,
            },
            "opt": {"world_model": wm_opt, "actor": actor_opt, "critic": critic_opt},
        }
        return new_state, metrics

    # step + fused-burst programs (scanned per-step inputs: key, tau)
    return build_train_burst(local_step, fabric, n_scanned=2)


def build_optimizers_and_state(cfg, params):
    """The three labeled optimizers + the initial agent-state pytree
    (shared with bench_dreamer.py so benchmarks can't drift from the real
    training wiring)."""
    world_tx = instantiate(
        cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients
    )
    actor_tx = instantiate(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients)
    critic_tx = instantiate(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients)
    agent_state = {
        "params": params,
        "opt": {
            "world_model": world_tx.init(params["world_model"]),
            "actor": actor_tx.init(params["actor"]),
            "critic": critic_tx.init(params["critic"]),
        },
    }
    return world_tx, actor_tx, critic_tx, agent_state


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    # These arguments cannot be changed (reference main :436-438)
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    # Environment setup — one process drives all devices (SPMD)
    n_envs = int(cfg.env.num_envs) * world_size
    # each env fault-tolerant via RestartOnException; vector backend
    # picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir, restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.cnn_keys.encoder == [] and cfg.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    if (
        len(set(cfg.cnn_keys.encoder).intersection(set(cfg.cnn_keys.decoder))) == 0
        and len(set(cfg.mlp_keys.encoder).intersection(set(cfg.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if len(set(cfg.cnn_keys.decoder) - set(cfg.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.cnn_keys.decoder))}"
        )
    if len(set(cfg.mlp_keys.decoder) - set(cfg.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.mlp_keys.decoder))}"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)
        fabric.print("Decoder CNN keys:", cfg.cnn_keys.decoder)
        fabric.print("Decoder MLP keys:", cfg.mlp_keys.decoder)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    root_key, build_key = jax.random.split(root_key)
    world_model, actor, critic, params = build_agent(
        cfg, actions_dim, is_continuous, observation_space, build_key
    )
    world_tx, actor_tx, critic_tx, agent_state = build_optimizers_and_state(cfg, params)

    expl_decay_steps = 0
    state = None
    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "expl_decay_steps": 0,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        expl_decay_steps = int(np.asarray(state["expl_decay_steps"]))
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(agent_state, fabric.replicated)

    train_fn = build_train_fn(
        world_model,
        actor,
        critic,
        world_tx,
        actor_tx,
        critic_tx,
        cfg,
        fabric,
        actions_dim,
        is_continuous,
    )
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)

    # the player acts on the CPU host with mirrored snapshots (utils/host.py)
    wm_mirror = HostParamMirror.from_cfg(agent_state["params"]["world_model"], fabric, cfg)
    actor_mirror = HostParamMirror.from_cfg(agent_state["params"]["actor"], fabric, cfg)
    play_wm = wm_mirror(agent_state["params"]["world_model"])
    play_actor = actor_mirror(agent_state["params"]["actor"])

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    # Buffer: sequential (per-env sub-buffers) or whole-episode storage
    # (reference main :545-564)
    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        kind="dreamer",
        obs_keys=obs_keys,
        min_size=8,
        dry_run_size=8,
        sequence_length=int(cfg.per_rank_sequence_length),
    )
    episode_buffer = str(cfg.buffer.get("type", "sequential")).lower() == "episode"
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    updates_before_training = (
        cfg.algo.train_every // policy_steps_per_update if not cfg.dry_run else 0
    )
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.checkpoint:
        learning_starts += start_step
    max_step_expl_decay = cfg.algo.actor.max_step_expl_decay // (
        cfg.algo.per_rank_gradient_steps * world_size
    ) if cfg.algo.actor.max_step_expl_decay else 0
    expl_amount = float(cfg.algo.actor.expl_amount)
    if cfg.checkpoint.resume_from:
        expl_amount = polynomial_decay(
            expl_decay_steps,
            initial=cfg.algo.actor.expl_amount,
            final=cfg.algo.actor.expl_min,
            max_decay_steps=max_step_expl_decay,
        )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update}), so "
            "the metrics will be logged at the nearest greater multiple of the "
            "policy_steps_per_update value."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True (sequential buffers; the episode buffer falls
    # back), double-buffered host prefetch otherwise; the [n, L, B, ...]
    # burst arrives on device in one step and the per-gradient-step loop
    # below slices device arrays (no H2D per step)
    staging = make_replay_staging(
        cfg,
        fabric,
        rb,
        sequence_length=int(cfg.per_rank_sequence_length),
        batch_sharding=fabric.sharding(None, None, fabric.data_axis),
        seed=cfg.seed,
    )
    rb = staging.rb

    # First observation: a zero-action is_first row (reference main :614-632)
    o = envs.reset(seed=cfg.seed)[0]
    obs = prepare_obs(o, cnn_keys, mlp_keys, n_envs)
    step_data = {k: obs[k][None] for k in obs_keys}
    step_data["dones"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["actions"] = np.zeros((1, n_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, n_envs, 1), np.float32)
    rb.add(step_data)
    player_state = player_fns["init_states"](play_wm, n_envs)

    per_rank_gradient_steps = 0

    # Burst acting (tier b, howto/rollout_engine.md): K env steps per device
    # dispatch, K = env.act_burst; 1 reproduces the per-step path exactly.
    # The RSSM player state rides the burst carry next to the observation —
    # the host callback is the whole old loop body (env step, episode
    # bookkeeping, buffer adds) and applies episode resets with the same
    # (1 - mask) * state arithmetic the jitted reset path computes, so
    # trajectories do not depend on K.
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    n_sub = len(actions_dim)
    state_box = {
        "carry": {
            "obs": obs,
            "player": {k: np.asarray(v) for k, v in player_state.items()},
        },
        "policy_step": policy_step,
    }

    def _host_step_core(actions, real_actions, player_np):
        state_box["policy_step"] += n_envs
        # The next row's is_first mirrors the previous dones
        # (reference main :675)
        step_data["is_first"] = step_data["dones"].copy()
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            o, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated).astype(np.float32)

        if "restart_on_exception" in infos:
            for i, env_roe in enumerate(infos["restart_on_exception"]):
                if env_roe and not dones[i]:
                    if not episode_buffer:
                        # both the host copy and (when the ring is on) the
                        # HBM mirror are patched by the staging facade
                        staging.force_done_last(i)
                    step_data["is_first"][0, i] = 1.0

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        # Save the real next observation (reference main :692-708)
        next_obs_np = {k: np.asarray(o[k]) for k in o}
        dones_idxes = np.nonzero(dones.reshape(-1))[0].tolist()
        real_next_obs = {k: v.copy() for k, v in next_obs_np.items()}
        if "final_obs" in infos and len(dones_idxes) > 0:
            for idx in dones_idxes:
                fo = infos["final_obs"][idx]
                if fo is not None:
                    for k in real_next_obs:
                        if k in fo:
                            real_next_obs[k][idx] = np.asarray(fo[k])

        # Row t holds the action that led to observation t (reference :705-720)
        obs_row = prepare_obs(real_next_obs, cnn_keys, mlp_keys, n_envs)
        for k in obs_keys:
            step_data[k] = obs_row[k][None]
        rewards = np.asarray(rewards, np.float32).reshape(n_envs, 1)
        step_data["dones"] = dones.reshape(1, n_envs, 1)
        step_data["actions"] = actions.reshape(1, n_envs, -1).astype(np.float32)
        step_data["rewards"] = clip_rewards_fn(rewards)[None]
        rb.add(step_data)

        # The *player* continues from the autoreset observation
        new_obs = prepare_obs(next_obs_np, cnn_keys, mlp_keys, n_envs)

        if len(dones_idxes) > 0:
            reset_obs = prepare_obs(
                {k: next_obs_np[k][dones_idxes] for k in next_obs_np},
                cnn_keys,
                mlp_keys,
                len(dones_idxes),
            )
            reset_data = {k: reset_obs[k][None] for k in obs_keys}
            reset_data["dones"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["is_first"] = np.ones_like(reset_data["dones"])
            rb.add(reset_data, dones_idxes)

            step_data["dones"][:, dones_idxes] = 0.0
            reset_mask = np.zeros((n_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            # same arithmetic as player_fns["reset_states"], applied host-side
            keep = np.float32(1.0) - reset_mask
            player_np = {k: keep * v for k, v in player_np.items()}

        carry = {"obs": new_obs, "player": player_np}
        state_box["carry"] = carry
        return carry

    def _host_env_step(*args):
        actions_j = [np.asarray(a) for a in args[:n_sub]]
        player_np = {
            "actions": np.asarray(args[n_sub]),
            "recurrent": np.asarray(args[n_sub + 1]),
            "stochastic": np.asarray(args[n_sub + 2]),
        }
        actions = np.concatenate(actions_j, -1)
        if is_continuous:
            real_actions = actions
        else:
            real_actions = np.stack([np.argmax(a, axis=-1) for a in actions_j], axis=-1)
        return _host_step_core(actions, real_actions, player_np)

    def _act_fn(p, carry, key):
        # the key advances inside the jitted burst with the same split order
        # the per-step loop used (carried key first, act key second), so the
        # K=1 key stream is bitwise the per-step stream
        key, act_key = jax.random.split(key)
        norm_obs = normalize_obs_jnp(carry["obs"], cnn_keys)
        actions_j, new_player = player_fns["exploration_action"](
            p["wm"], p["actor"], carry["player"], norm_obs, act_key, p["expl"]
        )
        cb_args = tuple(actions_j) + (
            new_player["actions"],
            new_player["recurrent"],
            new_player["stochastic"],
        )
        return cb_args, key

    burst_actor = BurstActor(_act_fn, _host_env_step, state_box["carry"])

    # in-run eval (howto/evaluation.md): rank 0 publishes the frozen params
    # through the policy channel every eval.every_n_steps; a separate process
    # scores them, so nothing below touches the train-step critical path
    from sheeprl_tpu.evals.inrun import maybe_start_inrun_eval

    inrun = maybe_start_inrun_eval(fabric, cfg, log_dir)

    update = start_step
    while update <= num_updates:
        n_act, random_phase = train_gated_burst_plan(
            update,
            act_burst,
            learning_starts,
            num_updates,
            updates_before_training,
            resuming=cfg.checkpoint.resume_from is not None,
        )
        if random_phase:
            real_actions = actions = np.array(envs.action_space.sample())
            if not is_continuous:
                actions = np.concatenate(
                    [
                        np.eye(act_dim, dtype=np.float32)[act]
                        for act, act_dim in zip(
                            actions.reshape(len(actions_dim), -1), actions_dim
                        )
                    ],
                    axis=-1,
                )
            _host_step_core(actions, real_actions, state_box["carry"]["player"])
        else:
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, root_key = burst_actor.rollout(
                    {"wm": play_wm, "actor": play_actor, "expl": jnp.float32(expl_amount)},
                    state_box["carry"],
                    root_key,
                    n_act,
                )
            # the burst program commits its inputs to the player's device;
            # pull the carried key back to host numpy (uncommitted) so the
            # possibly multi-device train program keeps accepting it
            root_key = np.asarray(root_key)
        policy_step = state_box["policy_step"]

        update += n_act
        last = update - 1
        updates_before_training -= n_act

        # Train the agent (reference main :756-800)
        if last >= learning_starts and updates_before_training <= 0:
            n_samples = (
                cfg.algo.per_rank_pretrain_steps
                if last == learning_starts
                else cfg.algo.per_rank_gradient_steps
            )
            metrics = None
            if n_samples > 0:
                # a length-0 scan over the burst would fail at trace time;
                # n_samples<=0 degrades to "no training this window"
                local_data = staging.sample_device(
                    cfg.per_rank_batch_size * world_size,
                    sequence_length=cfg.per_rank_sequence_length,
                    n_samples=n_samples,
                )
                # hard target copies on the host-computed cadence; metrics
                # are pulled at most once per burst behind the shared gate
                taus = tau_schedule(
                    n_samples,
                    per_rank_gradient_steps,
                    cfg.algo.critic.target_network_update_freq,
                    tau=1.0,
                    first_hard=False,
                )
                fetch_metrics = metric_fetch_gate(
                    cfg,
                    aggregator,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    update=last,
                    num_updates=num_updates,
                    policy_steps_per_update=policy_steps_per_update,
                    world_size=world_size,
                )
                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    # the whole burst (n_samples gradient steps) is ONE
                    # scanned dispatch (sheeprl_tpu/train): per-call overhead
                    # on a remote-attached device would otherwise repeat per
                    # gradient step
                    root_key, train_key = jax.random.split(root_key)
                    agent_state, metrics, _ = run_train_burst(
                        train_fn,
                        agent_state,
                        local_data,
                        (jax.random.split(train_key, n_samples), jnp.asarray(taus)),
                        world_size=world_size,
                        fetch_metrics=fetch_metrics,
                    )
                    per_rank_gradient_steps += n_samples
                    play_wm = wm_mirror(agent_state["params"]["world_model"])
                    play_actor = actor_mirror(agent_state["params"]["actor"])
                    train_step += world_size
            updates_before_training = cfg.algo.train_every // policy_steps_per_update
            if cfg.algo.actor.expl_decay:
                expl_decay_steps += 1
                expl_amount = polynomial_decay(
                    expl_decay_steps,
                    initial=cfg.algo.actor.expl_amount,
                    final=cfg.algo.actor.expl_min,
                    max_decay_steps=max_step_expl_decay,
                )
            if aggregator and not aggregator.disabled:
                if metrics is not None:
                    for k, v in metrics.items():
                        if k in aggregator:
                            aggregator.update(k, float(np.asarray(v)))
                if "Params/exploration_amount" in aggregator:
                    aggregator.update("Params/exploration_amount", expl_amount)

        if inrun is not None and last >= learning_starts and inrun.due(policy_step):
            # versioned by policy_step; the npz write runs on the publisher's
            # writer thread, so the cost here is one params-sized device_get
            inrun.maybe_publish(
                policy_step,
                {"agent": {"params": jax.device_get(agent_state["params"])}},
            )

        # Log metrics
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        # Checkpoint
        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "expl_decay_steps": expl_decay_steps,
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    if inrun is not None:
        inrun.close()
    staging.close()
    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(
            player_fns,
            jax.device_get(agent_state["params"]),
            fabric,
            cfg,
            log_dir,
            sample_actions=False,
            normalize_fn=normalize_obs_jnp,
        )
