"""DreamerV2 world-model loss (reference ``sheeprl/algos/dreamer_v2/loss.py``:
reconstruction_loss :11-105).

Eq. 2 of the DV2 paper: Gaussian NLL of observations/rewards (+ optional
Bernoulli continue NLL) plus the KL-*balanced* categorical state loss —
``alpha · KL(sg(post) ‖ prior) + (1−alpha) · KL(post ‖ sg(prior))`` with the
free-nats clamp applied to the mean (``kl_free_avg``) or element-wise.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.distributions import Independent, OneHotCategorical, kl_divergence

sg = jax.lax.stop_gradient


def categorical_kl(p_logits: jnp.ndarray, q_logits: jnp.ndarray) -> jnp.ndarray:
    """KL( Cat(p) ‖ Cat(q) ) summed over the stochastic dim.
    Logits ``[..., S, D]`` → ``[...]``."""
    p = Independent(OneHotCategorical(logits=p_logits), 1)
    q = Independent(OneHotCategorical(logits=q_logits), 1)
    return kl_divergence(p, q)


def reconstruction_loss(
    po: Dict[str, Any],
    observations: Dict[str, jnp.ndarray],
    pr: Any,
    rewards: jnp.ndarray,
    priors_logits: jnp.ndarray,
    posteriors_logits: jnp.ndarray,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    pc: Optional[Any] = None,
    continue_targets: Optional[jnp.ndarray] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """``priors_logits``/``posteriors_logits``: ``[T, B, S, D]``.
    Returns ``(scalar_loss, metrics)`` (the reference returns a 6-tuple)."""
    observation_loss = -sum(jnp.mean(po[k].log_prob(observations[k])) for k in po)
    reward_loss = -jnp.mean(pr.log_prob(rewards))

    lhs = categorical_kl(sg(posteriors_logits), priors_logits)
    rhs = categorical_kl(posteriors_logits, sg(priors_logits))
    free = jnp.asarray(kl_free_nats, lhs.dtype)
    if kl_free_avg:
        loss_lhs = jnp.maximum(jnp.mean(lhs), free)
        loss_rhs = jnp.maximum(jnp.mean(rhs), free)
    else:
        loss_lhs = jnp.mean(jnp.maximum(lhs, free))
        loss_rhs = jnp.mean(jnp.maximum(rhs, free))
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs

    continue_loss = jnp.zeros(())
    if pc is not None and continue_targets is not None:
        continue_loss = discount_scale_factor * -jnp.mean(pc.log_prob(continue_targets))

    total = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    metrics = {
        "Loss/world_model_loss": total,
        "Loss/observation_loss": observation_loss,
        "Loss/reward_loss": reward_loss,
        "Loss/state_loss": kl_loss,
        "Loss/continue_loss": continue_loss,
        "State/kl": jnp.mean(lhs),
        "State/post_entropy": jnp.mean(
            Independent(OneHotCategorical(logits=sg(posteriors_logits)), 1).entropy()
        ),
        "State/prior_entropy": jnp.mean(
            Independent(OneHotCategorical(logits=sg(priors_logits)), 1).entropy()
        ),
    }
    return total, metrics
