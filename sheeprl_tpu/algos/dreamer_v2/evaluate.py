"""DreamerV2 evaluation (reference ``sheeprl/algos/dreamer_v2/evaluate.py``),
collapsed onto the shared eval service via the common dreamer-family
builder."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax

from sheeprl_tpu.algos.dreamer_v2.agent import build_agent, build_player_fns
from sheeprl_tpu.algos.dreamer_v2.utils import normalize_obs_jnp
from sheeprl_tpu.evals.builders import actions_dim_of, dreamer_eval_policy
from sheeprl_tpu.evals.service import EvalPolicy, register_eval_builder, run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_eval_builder(algorithms=["dreamer_v2"])
def dreamer_v2_eval_policy(fabric, cfg, state, observation_space, action_space) -> EvalPolicy:
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    actions_dim, is_continuous = actions_dim_of(action_space)
    world_model, actor, _, _ = build_agent(
        cfg, actions_dim, is_continuous, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    params = params_on_device(state["agent"]["params"])
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)
    return dreamer_eval_policy(
        player_fns, params, cfg, is_continuous, normalize_fn=normalize_obs_jnp
    )


@register_evaluation(algorithms=["dreamer_v2"])
def evaluate_dreamer_v2(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
