"""DreamerV2 utilities (reference ``sheeprl/algos/dreamer_v2/utils.py``).

- :data:`AGGREGATOR_KEYS` — the metric allow-list (reference :19-36).
- :func:`compute_lambda_values` — the V2 TD(λ) recursion *with bootstrap*
  (reference :82-99) as one reversed ``lax.scan``.
- obs preparation/normalization: V2 pixels are scaled to ``[-0.5, 0.5]``
  (reference train :112 — ``/255 − 0.5``).
- :func:`test` re-exports the DV3 greedy-rollout helper (identical contract).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/post_entropy",
    "State/prior_entropy",
    "State/kl",
    "Params/exploration_amount",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}


def compute_lambda_values(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    continues: jnp.ndarray,
    bootstrap: jnp.ndarray,
    lmbda: float = 0.95,
) -> jnp.ndarray:
    """TD(λ) over ``[H, ...]`` with an explicit bootstrap row (reference
    dv2/utils.py:82-99): ``lv_t = r_t + c_t·( (1−λ)·v_{t+1} + λ·lv_{t+1} )``
    with ``lv_{H} = bootstrap``. ``bootstrap`` is ``[1, ...]``."""
    next_values = jnp.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def step(agg, inp):
        interm, cont = inp
        agg = interm + cont * lmbda * agg
        return agg, agg

    _, lv = jax.lax.scan(step, bootstrap[0], (inputs, continues), reverse=True)
    return lv


def normalize_obs_jnp(obs: Dict[str, jnp.ndarray], cnn_keys) -> Dict[str, jnp.ndarray]:
    """uint8 pixels → [-0.5, 0.5] floats on device (reference /255 − 0.5)."""
    return {
        k: (
            jnp.asarray(v, jnp.float32) / 255.0 - 0.5
            if k in cnn_keys
            else jnp.asarray(v, jnp.float32)
        )
        for k, v in obs.items()
    }
