"""DreamerV2 agent — flax modules, functional player, Xavier init.

Behavioral contract from the reference ``sheeprl/algos/dreamer_v2/agent.py``
(CNNEncoder :33-76, MLPEncoder :78-123, CNNDecoder :125-193, MLPDecoder
:196-240, RecurrentModel :243-291, RSSM :294-411, Actor :413-585,
PlayerDV2 :620-770, build_agent :772-1030).

Differences from the DV3 chassis (``algos/dreamer_v3/agent.py``) that define
the V2 model family:

- conv stages are k=4/s=2/**valid** padding (31→14→6→2 on 64×64) and the
  decoder inverts them from a 1×1 map with kernels [5, 5, 6, 6];
- ELU activations, no LayerNorm by default (except inside the GRU cell),
  biases always on;
- the categorical latent has **no** 1% uniform-mix, and an ``is_first`` reset
  zeroes the carried state instead of re-initialising from the prior
  (reference RSSM.dynamic :327-363);
- observations are decoded as unit-variance Gaussians, rewards/values are
  1-dim Gaussian heads (no two-hot), and every kernel gets Xavier-normal
  init (reference init_weights, dreamer_v2/utils.py:62-79).

The time loop still lives in the caller as ``jax.lax.scan`` and the player is
an explicit state pytree — the TPU-native design notes in the DV3 module
apply here unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# The actor trunk/head layout and the distribution/sampling/exploration
# helpers are structurally identical between V2 and V3 (the reference's DV3
# Actor subclasses the DV2 one); V2 passes unimix=0 and its own defaults.
from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    actor_entropy,
    add_exploration_noise,
    build_actor_dists,
    resolve_actor_distribution,
    sample_actor_actions,
)
from sheeprl_tpu import kernels
from sheeprl_tpu.models import MLP, CNN, DeCNN, LayerNormGRUCell

sg = jax.lax.stop_gradient

__all__ = [
    "Actor",
    "CNNEncoder",
    "MLPEncoder",
    "CNNDecoder",
    "MLPDecoder",
    "RecurrentModel",
    "RSSM",
    "WorldModel",
    "MLPHead",
    "actor_entropy",
    "add_exploration_noise",
    "build_actor_dists",
    "build_agent",
    "build_player_fns",
    "resolve_actor_distribution",
    "sample_actor_actions",
    "xavier_normal_initialization",
]


# ---------------------------------------------------------------------------
# encoders / decoders
# ---------------------------------------------------------------------------


class CNNEncoder(nn.Module):
    """Image encoder (reference agent.py:33-76): 4 conv stages of k=4/s=2
    with *valid* padding and channels ``[1, 2, 4, 8] × multiplier``; optional
    channel-last LayerNorm; flattened output. Input ``[..., C, H, W]``."""

    keys: Sequence[str]
    channels_multiplier: int
    layer_norm: bool = False
    activation: Any = "elu"

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        return CNN(
            channels=[m * self.channels_multiplier for m in (1, 2, 4, 8)],
            kernel_sizes=4,
            strides=2,
            paddings=0,
            activation=self.activation,
            layer_norm=self.layer_norm,
            flatten=True,
        )(x)


def cnn_encoder_output_dim(image_size: Tuple[int, int], channels_multiplier: int) -> int:
    """Static shape math replacing the reference's dummy-forward probe
    (agent.py:70-71): four valid k=4/s=2 stages."""
    h, w = image_size
    for _ in range(4):
        h = (h - 4) // 2 + 1
        w = (w - 4) // 2 + 1
    return 8 * channels_multiplier * h * w


class MLPEncoder(nn.Module):
    """Vector encoder (reference agent.py:78-123): N dense blocks, no symlog."""

    keys: Sequence[str]
    mlp_layers: int = 4
    dense_units: int = 400
    layer_norm: bool = False
    activation: Any = "elu"

    @nn.compact
    def __call__(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
        )(x)


class CNNDecoder(nn.Module):
    """Pixel decoder (reference agent.py:125-193): Linear projection of the
    latent to the encoder's flat feature size, reshaped to a 1×1 map, then
    four transposed convs (k=[5,5,6,6], s=2) back to the image."""

    output_channels: Sequence[int]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    layer_norm: bool = False
    activation: Any = "elu"

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> jnp.ndarray:
        total_c = sum(self.output_channels)
        x = nn.Dense(self.cnn_encoder_output_dim)(latent)
        lead = x.shape[:-1]
        x = jnp.reshape(x, lead + (self.cnn_encoder_output_dim, 1, 1))
        return DeCNN(
            channels=[m * self.channels_multiplier for m in (4, 2, 1)] + [total_c],
            kernel_sizes=[5, 5, 6, 6],
            strides=2,
            paddings=0,
            activation=self.activation,
            layer_norm=[self.layer_norm] * 3 + [False],
        )(x)


class MLPDecoder(nn.Module):
    """Vector decoder (reference agent.py:196-240): dense trunk + one linear
    head per key."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 400
    layer_norm: bool = False
    activation: Any = "elu"

    @nn.compact
    def __call__(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
        )(latent)
        return {
            k: nn.Dense(dim, name=f"head_{k}")(x)
            for k, dim in zip(self.keys, self.output_dims)
        }


# ---------------------------------------------------------------------------
# recurrent model / RSSM
# ---------------------------------------------------------------------------


class RecurrentModel(nn.Module):
    """Dense pre-layer + LayerNorm GRU cell (reference agent.py:243-291; the
    cell always norms, the pre-layer only if asked)."""

    recurrent_state_size: int
    dense_units: int
    layer_norm: bool = False
    activation: Any = "elu"
    fused: str = "off"  # resolved kernel tier (sheeprl_tpu/kernels)

    @nn.compact
    def __call__(self, x: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
        feat = MLP(
            hidden_sizes=[self.dense_units],
            activation=self.activation,
            layer_norm=self.layer_norm,
        )(x)
        return LayerNormGRUCell(
            self.recurrent_state_size, bias=True, layer_norm=True, norm_eps=1e-5, name="gru",
            fused=self.fused,
        )(feat, h)


class _StochasticModel(nn.Module):
    """MLP trunk + logits head — shared shape of the transition (prior) and
    representation (posterior) models (reference build_agent :857-886)."""

    hidden_size: int
    stoch_size: int  # stochastic_size * discrete_size
    layer_norm: bool = False
    activation: Any = "elu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = MLP(
            hidden_sizes=[self.hidden_size],
            activation=self.activation,
            layer_norm=self.layer_norm,
        )(x)
        return nn.Dense(self.stoch_size, name="head")(x)


def compute_stochastic_state(
    logits: jnp.ndarray,
    discrete: int,
    key: Optional[jax.Array],
    sample: bool = True,
    gumbel: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Sample (straight-through) or take the mode of the categorical latent
    (reference dreamer_v2/utils.py:39-58). ``logits`` flat ``[..., S*D]`` →
    flat state ``[..., S*D]``.

    ``gumbel`` ([..., S, D]) is pre-drawn Gumbel(0,1) noise — train scans
    draw it for the whole sequence outside the time loop (see the DV3 agent's
    ``compute_stochastic_state``)."""
    from sheeprl_tpu.distributions import OneHotCategoricalStraightThrough

    shape = logits.shape
    logits = jnp.reshape(logits, shape[:-1] + (-1, discrete))
    if sample and gumbel is not None:
        one = jax.nn.one_hot(
            jnp.argmax(logits + gumbel, axis=-1), discrete, dtype=logits.dtype
        )
        probs = jax.nn.softmax(logits, axis=-1)
        state = one + probs - jax.lax.stop_gradient(probs)
        return jnp.reshape(state, shape)
    dist = OneHotCategoricalStraightThrough(logits=logits)
    state = dist.rsample(key) if sample else dist.mode
    return jnp.reshape(state, shape)


class RSSM(nn.Module):
    """Discrete-latent RSSM (reference agent.py:294-411): no unimix, and an
    ``is_first`` step zeroes the carried action/posterior/recurrent state.

    All methods are single-step over a batch; callers scan them over time.
    The stochastic state is carried *flat* ``[..., S*D]``.
    """

    recurrent_state_size: int
    stochastic_size: int
    discrete_size: int
    dense_units: int
    hidden_size: int
    representation_hidden_size: Optional[int] = None
    layer_norm: bool = False
    recurrent_layer_norm: bool = True
    activation: Any = "elu"
    fused: str = "off"

    def setup(self):
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            layer_norm=self.recurrent_layer_norm,
            activation=self.activation,
            fused=self.fused,
        )
        stoch = self.stochastic_size * self.discrete_size
        self.representation_model = _StochasticModel(
            hidden_size=self.representation_hidden_size or self.hidden_size,
            stoch_size=stoch,
            layer_norm=self.layer_norm,
            activation=self.activation,
        )
        self.transition_model = _StochasticModel(
            hidden_size=self.hidden_size,
            stoch_size=stoch,
            layer_norm=self.layer_norm,
            activation=self.activation,
        )

    def _transition(
        self,
        recurrent_out: jnp.ndarray,
        key: Optional[jax.Array],
        sample_state: bool = True,
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        logits = self.transition_model(recurrent_out)
        return logits, compute_stochastic_state(
            logits, self.discrete_size, key, sample=sample_state, gumbel=gumbel
        )

    def _representation(
        self,
        recurrent_state: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        key: Optional[jax.Array],
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        logits = self.representation_model(
            jnp.concatenate([recurrent_state, embedded_obs], -1)
        )
        return logits, compute_stochastic_state(
            logits, self.discrete_size, key, gumbel=gumbel
        )

    def dynamic(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        is_first: jnp.ndarray,
        key: jax.Array,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One posterior step (reference :327-363): zero-mask resets, then
        recurrent → prior → posterior. Returns ``(recurrent_state, posterior,
        posterior_logits, prior_logits)``."""
        recurrent_state, posterior, posterior_logits = self.dynamic_posterior(
            posterior, recurrent_state, action, embedded_obs, is_first, key
        )
        prior_logits = self.prior_logits(recurrent_state)
        return recurrent_state, posterior, posterior_logits, prior_logits

    def dynamic_posterior(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        is_first: jnp.ndarray,
        key: Optional[jax.Array],
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Sequential core of ``dynamic``: the prior (transition) logits never
        feed back into the time loop, so train scans run this reduced step and
        batch :meth:`prior_logits` over the whole [T, B] output afterwards
        (same optimization as the DV3 RSSM)."""
        action = (1.0 - is_first) * action
        posterior = (1.0 - is_first) * posterior
        recurrent_state = (1.0 - is_first) * recurrent_state
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        if gumbel is None:
            # same split as dynamic() (whose k1 sampled the discarded prior)
            key = jax.random.split(key)[1]
        posterior_logits, posterior = self._representation(
            recurrent_state, embedded_obs, key, gumbel=gumbel
        )
        return recurrent_state, posterior, posterior_logits

    def prior_logits(self, recurrent_states: jnp.ndarray) -> jnp.ndarray:
        """Transition logits — batchable over any leading shape."""
        return self.transition_model(recurrent_states)

    def imagination(
        self,
        prior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        actions: jnp.ndarray,
        key: Optional[jax.Array],
        gumbel: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """One prior step in imagination (reference :396-411)."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([prior, actions], -1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key, gumbel=gumbel)
        return imagined_prior, recurrent_state

    def __call__(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        return self.dynamic(posterior, recurrent_state, action, embedded_obs, is_first, key)


# ---------------------------------------------------------------------------
# world model
# ---------------------------------------------------------------------------


class MLPHead(nn.Module):
    """Dense trunk + single linear head (reward / continue / critic shape,
    reference build_agent :888-921 — plain Gaussian/Bernoulli heads)."""

    output_dim: int
    mlp_layers: int
    dense_units: int
    layer_norm: bool = False
    activation: Any = "elu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
        )(x)
        return nn.Dense(self.output_dim, name="head")(x)


class WorldModel(nn.Module):
    """Encoder + RSSM + observation/reward/[continue] heads (the canonical
    container, reference agent.py:714-739). Methods are exposed for
    ``apply(..., method=...)`` so train steps call exactly what they need
    inside ``lax.scan``."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels: Sequence[int]
    mlp_dims: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    encoder_mlp_layers: int
    decoder_mlp_layers: int
    dense_units: int
    recurrent_state_size: int
    stochastic_size: int
    discrete_size: int
    hidden_size: int
    representation_hidden_size: Optional[int] = None
    reward_mlp_layers: Optional[int] = None
    reward_dense_units: Optional[int] = None
    continue_mlp_layers: Optional[int] = None
    continue_dense_units: Optional[int] = None
    use_continues: bool = False
    layer_norm: bool = False
    cnn_act: Any = "elu"
    dense_act: Any = "elu"
    fused: str = "off"

    def setup(self):
        if self.cnn_keys:
            self.cnn_encoder = CNNEncoder(
                keys=self.cnn_keys,
                channels_multiplier=self.channels_multiplier,
                layer_norm=self.layer_norm,
                activation=self.cnn_act,
            )
            self.cnn_decoder = CNNDecoder(
                output_channels=self.cnn_channels,
                channels_multiplier=self.channels_multiplier,
                cnn_encoder_output_dim=cnn_encoder_output_dim(
                    self.image_size, self.channels_multiplier
                ),
                layer_norm=self.layer_norm,
                activation=self.cnn_act,
            )
        if self.mlp_keys:
            self.mlp_encoder = MLPEncoder(
                keys=self.mlp_keys,
                mlp_layers=self.encoder_mlp_layers,
                dense_units=self.dense_units,
                layer_norm=self.layer_norm,
                activation=self.dense_act,
            )
            self.mlp_decoder = MLPDecoder(
                keys=self.mlp_keys,
                output_dims=self.mlp_dims,
                mlp_layers=self.decoder_mlp_layers,
                dense_units=self.dense_units,
                layer_norm=self.layer_norm,
                activation=self.dense_act,
            )
        self.rssm = RSSM(
            recurrent_state_size=self.recurrent_state_size,
            stochastic_size=self.stochastic_size,
            discrete_size=self.discrete_size,
            dense_units=self.dense_units,
            hidden_size=self.hidden_size,
            representation_hidden_size=self.representation_hidden_size,
            layer_norm=self.layer_norm,
            activation=self.dense_act,
            fused=self.fused,
        )
        self.reward_model = MLPHead(
            output_dim=1,
            mlp_layers=self.reward_mlp_layers or self.decoder_mlp_layers,
            dense_units=self.reward_dense_units or self.dense_units,
            layer_norm=self.layer_norm,
            activation=self.dense_act,
        )
        if self.use_continues:
            self.continue_model = MLPHead(
                output_dim=1,
                mlp_layers=self.continue_mlp_layers or self.decoder_mlp_layers,
                dense_units=self.continue_dense_units or self.dense_units,
                layer_norm=self.layer_norm,
                activation=self.dense_act,
            )

    # -- methods for apply(..., method=...) --------------------------------

    def encode(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        feats = []
        if self.cnn_keys:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_keys:
            feats.append(self.mlp_encoder(obs))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        return self.rssm.dynamic(posterior, recurrent_state, action, embedded_obs, is_first, key)

    def dynamic_posterior(
        self, posterior, recurrent_state, action, embedded_obs, is_first, key, gumbel=None
    ):
        return self.rssm.dynamic_posterior(
            posterior, recurrent_state, action, embedded_obs, is_first, key, gumbel
        )

    def prior_logits(self, recurrent_states):
        return self.rssm.prior_logits(recurrent_states)

    def imagination(self, prior, recurrent_state, actions, key, gumbel=None):
        return self.rssm.imagination(prior, recurrent_state, actions, key, gumbel=gumbel)

    def recurrent_step(self, stochastic, actions, recurrent_state):
        return self.rssm.recurrent_model(
            jnp.concatenate([stochastic, actions], -1), recurrent_state
        )

    def representation(self, recurrent_state, embedded_obs, key):
        return self.rssm._representation(recurrent_state, embedded_obs, key)

    def decode(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {}
        if self.cnn_keys:
            rec = self.cnn_decoder(latent)
            if len(self.cnn_keys) > 1:
                parts = jnp.split(rec, np.cumsum(np.asarray(self.cnn_channels))[:-1], axis=-3)
            else:
                parts = [rec]
            out.update({k: v for k, v in zip(self.cnn_keys, parts)})
        if self.mlp_keys:
            out.update(self.mlp_decoder(latent))
        return out

    def reward(self, latent: jnp.ndarray) -> jnp.ndarray:
        return self.reward_model(latent)

    def continues(self, latent: jnp.ndarray) -> jnp.ndarray:
        return self.continue_model(latent)

    def __call__(self, obs, posterior, recurrent_state, action, is_first, key):
        """Init-path: touches every submodule once."""
        embed = self.encode(obs)
        recurrent_state, posterior, post_logits, prior_logits = self.rssm.dynamic(
            posterior, recurrent_state, action, embed, is_first, key
        )
        latent = jnp.concatenate([posterior, recurrent_state], -1)
        recon = self.decode(latent)
        cont = self.continue_model(latent) if self.use_continues else None
        return (
            recurrent_state,
            posterior,
            post_logits,
            prior_logits,
            recon,
            self.reward_model(latent),
            cont,
        )


# ---------------------------------------------------------------------------
# Xavier-normal initialization (reference init_weights, dv2/utils.py:62-79)
# ---------------------------------------------------------------------------


from sheeprl_tpu.algos.dreamer_v3.agent import _fans  # noqa: E402


def xavier_normal_initialization(params: Dict[str, Any], key: jax.Array) -> Dict[str, Any]:
    """Re-initialize every kernel with Xavier normal, biases zero (the
    reference applies ``nn.init.xavier_normal_`` to every Linear/Conv via
    ``.apply(init_weights)``, build_agent :1008-1016)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    keys = jax.random.split(key, max(len(flat), 1))
    leaves = []
    for i, (path, leaf) in enumerate(flat):
        name = "/".join(getattr(p, "key", str(p)) for p in path)
        if name.endswith("kernel") and leaf.ndim >= 2:
            fan_in, fan_out = _fans(leaf.shape)
            std = float(np.sqrt(2.0 / (fan_in + fan_out)))
            leaves.append(std * jax.random.normal(keys[i], leaf.shape, leaf.dtype))
        elif name.endswith("bias"):
            leaves.append(jnp.zeros_like(leaf))
        else:
            leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    observation_space,
    key: jax.Array,
) -> Tuple[WorldModel, Actor, MLPHead, Dict[str, Any]]:
    """Construct module defs + initialized params (reference build_agent,
    agent.py:772-1030). Returns ``(world_model, actor, critic, params)`` with
    ``params = {world_model, actor, critic, target_critic}``."""
    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    screen = int(cfg.env.screen_size)
    cnn_channels = [int(np.prod(observation_space[k].shape[:-2])) for k in cnn_keys]
    mlp_dims = [int(np.prod(observation_space[k].shape)) for k in mlp_keys]
    # resolve the fused-kernel tier once, here: the string is baked into the
    # module tree so every train/player/imagination call sites agree
    fused = kernels.resolve_tier(cfg.algo.get("fused_kernels", "off"), family="hafner_ln_gru")

    world_model = WorldModel(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_channels=cnn_channels,
        mlp_dims=mlp_dims,
        image_size=(screen, screen),
        channels_multiplier=int(wm_cfg.encoder.cnn_channels_multiplier),
        encoder_mlp_layers=int(wm_cfg.encoder.mlp_layers),
        decoder_mlp_layers=int(wm_cfg.observation_model.mlp_layers),
        dense_units=int(wm_cfg.encoder.dense_units),
        recurrent_state_size=int(wm_cfg.recurrent_model.recurrent_state_size),
        stochastic_size=int(wm_cfg.stochastic_size),
        discrete_size=int(wm_cfg.discrete_size),
        hidden_size=int(wm_cfg.transition_model.hidden_size),
        representation_hidden_size=int(wm_cfg.representation_model.hidden_size),
        reward_mlp_layers=int(wm_cfg.reward_model.mlp_layers),
        reward_dense_units=int(wm_cfg.reward_model.dense_units),
        continue_mlp_layers=int(wm_cfg.discount_model.mlp_layers),
        continue_dense_units=int(wm_cfg.discount_model.dense_units),
        use_continues=bool(wm_cfg.use_continues),
        layer_norm=bool(cfg.algo.layer_norm),
        cnn_act=cfg.algo.cnn_act,
        dense_act=cfg.algo.dense_act,
        fused=fused,
    )
    latent_size = (
        int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
        + int(wm_cfg.recurrent_model.recurrent_state_size)
    )
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=resolve_actor_distribution(
            cfg.distribution.get("type", "auto"), is_continuous
        ),
        dense_units=int(cfg.algo.actor.dense_units),
        mlp_layers=int(cfg.algo.actor.mlp_layers),
        layer_norm=bool(cfg.algo.actor.layer_norm),
        activation=cfg.algo.actor.dense_act,
    )
    critic = MLPHead(
        output_dim=1,
        mlp_layers=int(cfg.algo.critic.mlp_layers),
        dense_units=int(cfg.algo.critic.dense_units),
        layer_norm=bool(cfg.algo.critic.layer_norm),
        activation=cfg.algo.critic.dense_act,
    )

    k_wm, k_actor, k_critic, k_xw, k_xa, k_xc, k_s = jax.random.split(key, 7)
    dummy_obs = {}
    for k, ch in zip(cnn_keys, cnn_channels):
        dummy_obs[k] = jnp.zeros((1, ch, screen, screen), jnp.float32)
    for k, dim in zip(mlp_keys, mlp_dims):
        dummy_obs[k] = jnp.zeros((1, dim), jnp.float32)
    stoch = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec = int(wm_cfg.recurrent_model.recurrent_state_size)
    act_dim = int(np.sum(actions_dim))

    wm_params = world_model.init(
        k_wm,
        dummy_obs,
        jnp.zeros((1, stoch)),
        jnp.zeros((1, rec)),
        jnp.zeros((1, act_dim)),
        jnp.zeros((1, 1)),
        k_s,
    )["params"]
    actor_params = actor.init(k_actor, jnp.zeros((1, latent_size)))["params"]
    critic_params = critic.init(k_critic, jnp.zeros((1, latent_size)))["params"]

    wm_params = xavier_normal_initialization(wm_params, k_xw)
    actor_params = xavier_normal_initialization(actor_params, k_xa)
    critic_params = xavier_normal_initialization(critic_params, k_xc)

    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
    }
    return world_model, actor, critic, params


# ---------------------------------------------------------------------------
# functional player (reference PlayerDV2, agent.py:620-770)
# ---------------------------------------------------------------------------


def build_player_fns(
    world_model: WorldModel,
    actor: Actor,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
):
    """Pure jitted player functions over an explicit state pytree
    ``{"actions", "recurrent", "stochastic"}`` — reference PlayerDV2's
    mutable attributes become ``jnp.where``-masked pytrees. All states
    init to zeros (reference init_states :706-716)."""
    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)
    rec_size = int(cfg.algo.world_model.recurrent_model.recurrent_state_size)
    stoch_flat = int(cfg.algo.world_model.stochastic_size) * int(
        cfg.algo.world_model.discrete_size
    )
    act_dim = int(np.sum(actions_dim))

    def init_states(wm_params, n_envs: int):
        del wm_params  # V2 inits to zeros; signature shared with the V3 player
        return {
            "actions": jnp.zeros((n_envs, act_dim)),
            "recurrent": jnp.zeros((n_envs, rec_size)),
            "stochastic": jnp.zeros((n_envs, stoch_flat)),
        }

    def reset_states(wm_params, state, reset_mask):
        del wm_params
        return jax.tree_util.tree_map(lambda s: (1.0 - reset_mask) * s, state)

    def _step(wm_params, actor_params, state, obs, key, is_training: bool):
        embed = world_model.apply({"params": wm_params}, obs, method=WorldModel.encode)
        recurrent = world_model.apply(
            {"params": wm_params},
            state["stochastic"],
            state["actions"],
            state["recurrent"],
            method=WorldModel.recurrent_step,
        )
        k_repr, k_act = jax.random.split(key)
        _, stochastic = world_model.apply(
            {"params": wm_params}, recurrent, embed, k_repr, method=WorldModel.representation
        )
        latent = jnp.concatenate([stochastic, recurrent], -1)
        pre_dist = actor.apply({"params": actor_params}, latent)
        dists = build_actor_dists(
            pre_dist, is_continuous, distribution, init_std, min_std, unimix=0.0
        )
        actions = sample_actor_actions(dists, is_continuous, k_act, is_training)
        new_state = {
            "actions": jnp.concatenate(actions, -1),
            "recurrent": recurrent,
            "stochastic": stochastic,
        }
        return actions, new_state

    @jax.jit
    def greedy_action(wm_params, actor_params, state, obs, key):
        return _step(wm_params, actor_params, state, obs, key, is_training=False)

    @jax.jit
    def exploration_action(wm_params, actor_params, state, obs, key, expl_amount):
        k_step, k_expl = jax.random.split(key)
        actions, new_state = _step(wm_params, actor_params, state, obs, k_step, is_training=True)
        expl = add_exploration_noise(actions, expl_amount, is_continuous, k_expl)
        new_state = dict(new_state, actions=jnp.concatenate(expl, -1))
        return expl, new_state

    return {
        "init_states": init_states,
        "reset_states": jax.jit(reset_states),
        "greedy_action": greedy_action,
        "exploration_action": exploration_action,
    }
