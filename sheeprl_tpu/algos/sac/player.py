"""SAC player loop for the actor–learner plane.

One function, :func:`run_player`, drives SAC collection in BOTH decoupled
modes: as a thread inside the learner process (``plane.num_players=0``, the
:class:`~sheeprl_tpu.plane.supervisor.LocalPlane` transport) and as a
spawned player process on the multi-process plane (imported by dotted name
from :mod:`sheeprl_tpu.plane.worker`). The loop:

- owns this player's slice of the env fleet (the canonical ``env_seeds``
  partition: player ``p`` with ``E`` envs gets seeds ``seed + p*E + i`` —
  player 0 of a 1-player plane is bitwise the thread-local seeding);
- acts through the PR-6 :class:`~sheeprl_tpu.envs.rollout.BurstActor` —
  the whole acting-loop body (env step, SAME_STEP final-obs fixup, episode
  bookkeeping, trajectory-row write) lives in the host callback, one policy
  dispatch per ``env.act_burst`` steps. Per-step keys are
  ``fold_in(player_key, update)`` *inside* the scanned body, so
  trajectories are burst-size-invariant and bitwise the historical
  per-step discipline;
- streams each burst as one trajectory slab (``ctx.writer`` — shared-memory
  slot in process mode, bounded queue in thread mode; either way the commit
  backpressures when the learner falls behind);
- hot-reloads published policy versions through ``ctx.wait_policy``: the
  deterministic version protocol of :mod:`sheeprl_tpu.plane.protocol`,
  loosened by ``plane.max_policy_lag``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

__all__ = ["run_player", "sac_slab_example"]


def sac_slab_example(
    capacity: int, n_envs: int, obs_dim: int, act_dim: int, store_next_obs: bool
) -> Dict[str, np.ndarray]:
    """Example arrays fixing the SAC trajectory-slab layout (one burst of up
    to ``capacity`` steps for ``n_envs`` envs)."""
    example = {
        "observations": np.zeros((capacity, n_envs, obs_dim), np.float32),
        "actions": np.zeros((capacity, n_envs, act_dim), np.float32),
        "rewards": np.zeros((capacity, n_envs, 1), np.float32),
        "dones": np.zeros((capacity, n_envs, 1), np.float32),
    }
    if store_next_obs:
        example["next_observations"] = np.zeros((capacity, n_envs, obs_dim), np.float32)
    return example


def run_player(ctx) -> None:
    """Collect updates ``[ctx.start_update, num_updates]`` for this player's
    env slice, one committed slab per collection burst."""
    import jax

    from sheeprl_tpu.algos.sac.agent import SACActor, action_bounds, squash_sample
    from sheeprl_tpu.algos.sac.utils import concat_obs
    from sheeprl_tpu.envs.rollout import BurstActor
    from sheeprl_tpu.envs.vector import env_seeds, make_vector_env
    from sheeprl_tpu.obs import span
    from sheeprl_tpu.plane.protocol import burst_plan
    from sheeprl_tpu.utils.metric import SumMetric

    cfg = ctx.cfg
    n_envs = int(ctx.n_envs)
    mlp_keys = list(cfg.mlp_keys.encoder)
    store_next_obs = not bool(cfg.buffer.sample_next_obs)

    if ctx.process_mode and cfg.env.get("vectorization", None) is None and cfg.env.get(
        "sync_env", None
    ) is None:
        # plane players default to the PR-5 shared-memory pool (bitwise
        # parity with sync is asserted by tests/test_envs/test_vector.py)
        cfg.env.vectorization = "async"
    if ctx.restart_count:
        # a respawned player must not replay the exact pre-crash trajectories:
        # offset this incarnation's env seeds (policy keys stay per-update)
        cfg.seed = int(cfg.seed) + 7919 * int(ctx.restart_count)

    envs = make_vector_env(
        cfg,
        fabric=None,
        log_dir=ctx.log_dir if ctx.player_idx == 0 else None,
        n_envs=n_envs,
        rank=ctx.env_rank,
    )
    try:
        _player_body(
            ctx, cfg, envs, env_seeds, n_envs, mlp_keys, store_next_obs,
            jax, SACActor, action_bounds, squash_sample, concat_obs,
            BurstActor, burst_plan, span, SumMetric,
        )
    finally:
        ctx.close_watchdog()
        envs.close()


def _player_body(
    ctx, cfg, envs, env_seeds, n_envs, mlp_keys, store_next_obs,
    jax, SACActor, action_bounds, squash_sample, concat_obs,
    BurstActor, burst_plan, span, SumMetric,
):
    import jax.numpy as jnp

    action_space = envs.single_action_space
    act_dim = int(np.prod(action_space.shape))
    action_scale, action_bias = action_bounds(action_space)
    scale_j, bias_j = jnp.asarray(action_scale), jnp.asarray(action_bias)
    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)

    # the random prefill draws from the vector action space's own rng —
    # seeded so thread and process players sample identical prefills
    envs.action_space.seed(int(cfg.seed) + 1_000_003 * (int(ctx.env_rank) + 1))

    # the per-player slice of the canonical seed sequence: ``reset(seed=int)``
    # would hand every player the SAME ``seed + i`` episode seeds — pass the
    # rank-partitioned list instead (rank 0 is bitwise the historical seeding)
    o = envs.reset(seed=env_seeds(int(cfg.seed), int(ctx.env_rank), n_envs))[0]
    obs = concat_obs(o, mlp_keys, n_envs)
    player_key = jnp.asarray(ctx.player_key)

    # mutable state the host callback and the burst loop share
    box: Dict[str, Any] = {"obs": obs, "views": None, "row": 0, "eps": [], "u": 0}

    def _host_env_step(actions):
        actions = np.asarray(actions)
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            next_o, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    box["eps"].append(
                        (float(fi["episode"]["r"][i]), float(fi["episode"]["l"][i]))
                    )

        next_obs = concat_obs(next_o, mlp_keys, n_envs)
        real_next_obs = next_obs.copy()
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    real_next_obs[idx] = concat_obs(final_obs, mlp_keys, 1)[0]

        views, r = box["views"], box["row"]
        views["observations"][r] = box["obs"]
        views["actions"][r] = np.asarray(actions, np.float32).reshape(n_envs, -1)
        views["rewards"][r] = np.asarray(rewards, np.float32).reshape(n_envs, 1)
        views["dones"][r] = np.asarray(dones, np.float32).reshape(n_envs, 1)
        if store_next_obs:
            views["next_observations"][r] = real_next_obs
        box["row"] = r + 1
        box["obs"] = next_obs
        box["u"] += 1
        ctx.beat()  # a hung envs.step() must fire the stall watchdog
        return {"obs": next_obs, "u": np.uint32(box["u"])}

    def _act_fn(actor_params, carry, key):
        # per-step key = fold_in(player_key, update) INSIDE the scan: bitwise
        # the historical per-step discipline, for every burst size
        step_key = jax.random.fold_in(key, carry["u"])
        mean, std = actor.apply({"params": actor_params}, carry["obs"])
        actions, _ = squash_sample(mean, std, step_key, scale_j, bias_j)
        return (actions,), key

    burst_actor = BurstActor(
        _act_fn, _host_env_step, {"obs": obs, "u": np.uint32(0)}
    )

    update = int(ctx.start_update)
    version = 0
    while update <= ctx.num_updates and not ctx.stop.is_set() and not ctx.orphaned():
        n_act, random_phase = burst_plan(
            update, ctx.act_burst, ctx.learning_starts, ctx.num_updates
        )
        params = None
        if not random_phase:
            version, params = ctx.wait_policy(update)
        token, views = ctx.acquire_slab()
        box["views"], box["row"], box["u"] = views, 0, update
        ep_stats: List[Tuple[float, float]] = []
        box["eps"] = ep_stats
        if random_phase:
            for _ in range(n_act):
                _host_env_step(envs.action_space.sample())
        else:
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                burst_actor.rollout(
                    params,
                    {"obs": box["obs"], "u": np.uint32(update)},
                    player_key,
                    n_act,
                )
        ctx.emit(token, views, update, n_act, version, ep_stats)
        update += n_act
