"""SAC, decoupled — actor–learner plane.

Behavioral contract from the reference ``sheeprl/algos/sac/sac_decoupled.py``
(main :32-60, player :63-270, trainer :273-548): dedicated environment
players keep feeding a replay buffer while trainers run one train round per
policy step and broadcast updated parameters back.

TPU-native design (``sheeprl_tpu/plane``, howto/actor_learner.md): this
entrypoint is the **learner**. Collection runs in the player loop
(:mod:`sheeprl_tpu.algos.sac.player`) on the execution plane selected by
``plane.num_players``:

- ``0`` (default) — one player *thread* streaming trajectory bursts over an
  in-memory bounded queue (:class:`~sheeprl_tpu.plane.supervisor.LocalPlane`);
- ``N > 0`` — N player *processes*, each owning its slice of the env fleet
  through the PR-5 async vector plane, streaming fixed-layout trajectory
  slabs over shared-memory ring queues with credited-slot backpressure
  (:class:`~sheeprl_tpu.plane.supervisor.ProcessPlane`), hot-reloading
  policy versions published atomically through the PR-2 checkpoint writer.

Both modes speak the same protocol (:mod:`sheeprl_tpu.plane.protocol`):
the learner trains update ``u-1`` while players collect ``u``, players act
on the version trained through ``u-2`` (plus ``plane.max_policy_lag``), so
a seeded 1-player plane run is bitwise the thread-local run — the
regression gate in ``tests/test_plane``. Requires ≥2 devices like the
reference.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import (
    SACActor,
    SACCritic,
    action_bounds,
    build_agent_state,
)
from sheeprl_tpu.algos.sac.player import run_player, sac_slab_example
from sheeprl_tpu.algos.sac.sac import build_train_fn
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.obs import (
    get_telemetry,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    register_train_cost,
    shape_specs,
    span,
)
from sheeprl_tpu.plane import (
    SlabSpec,
    build_plane,
    burst_plan,
    plane_env_split,
    version_after,
)
from sheeprl_tpu.replay import ReplayPlane, make_replay_buffer, replay_config
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, save_configs


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError("MineDojo is not currently supported by SAC agent")

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    if len(cfg.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.cnn_keys.encoder = []

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # the learner never steps envs — players own them (sac/player.py). One
    # probe env pins the wrapped spaces the whole plane agrees on.
    probe = make_eval_env(cfg, None, prefix="train")
    action_space = probe.action_space
    observation_space = probe.observation_space
    probe.close()
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"Provided environment: {cfg.env.id}"
            )

    act_dim = int(np.prod(action_space.shape))
    obs_dim = int(sum(np.prod(observation_space[k].shape) for k in cfg.mlp_keys.encoder))
    action_scale, action_bias = action_bounds(action_space)

    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    critic = SACCritic(hidden_size=cfg.algo.critic.hidden_size, num_critics=1)
    target_entropy = -float(act_dim)

    root_key, init_key = jax.random.split(root_key)
    agent_state = build_agent_state(
        actor, critic, init_key, int(cfg.algo.critic.n), obs_dim, act_dim, cfg.algo.alpha.alpha
    )

    qf_tx = instantiate(cfg.algo.critic.optimizer)
    actor_tx = instantiate(cfg.algo.actor.optimizer)
    alpha_tx = instantiate(cfg.algo.alpha.optimizer)
    opt_states = {
        "actor": actor_tx.init(agent_state["actor"]),
        "qf": qf_tx.init(agent_state["critics"]),
        "alpha": alpha_tx.init(agent_state["log_alpha"]),
    }

    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "opt_states": opt_states,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        opt_states = state["opt_states"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(agent_state, fabric.replicated)
    opt_states = jax.device_put(opt_states, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    # replay storage through the one factory (sheeprl_tpu/replay): shards=1 +
    # uniform returns the plain ReplayBuffer — bitwise the historical path;
    # replay.shards=N partitions the env axis so each player process owns
    # exactly one single-writer shard
    replay_cfg = replay_config(cfg)
    replay_shards = int(replay_cfg.get("shards", 1) or 1)
    num_players, envs_per_player = plane_env_split(cfg, n_envs)
    if replay_shards > 1 and replay_shards != num_players:
        raise ValueError(
            f"replay.shards={replay_shards} requires plane.num_players="
            f"{replay_shards} so each player process owns exactly one shard "
            f"(got plane.num_players={num_players})"
        )
    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=("observations",),
        dry_run_size=1,
        shards=replay_shards,
    )
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    needs_writeback = bool(getattr(rb, "needs_writeback", False))
    train_fn = build_train_fn(
        actor, critic, actor_tx, qf_tx, alpha_tx, cfg, fabric,
        action_scale, action_bias, target_entropy, donate=False,
        emit_td=needs_writeback,
    )
    batch_sharding = fabric.sharding(None, fabric.data_axis)
    # TPU-first replay staging (data/staging.py). The learner thread is the
    # only replay writer on the plane — player trajectories arrive as slabs
    # and land through rb.add below — so no cross-thread buffer lock is
    # needed anymore (the prefetch pipeline still binds its own).
    staging = make_replay_staging(
        cfg, fabric, rb, batch_sharding=batch_sharding, seed=cfg.seed
    )
    rb = staging.rb
    # zero-dispatch slab adoption (replay.adopt_slabs): sampled rows go
    # slab → HBM directly through the device ring instead of the
    # slab → host-rb → ring double copy
    adopt_slabs = bool(replay_cfg.get("adopt_slabs", False))
    if adopt_slabs and not staging.supports_adoption:
        warnings.warn(
            "replay.adopt_slabs=True needs the single-group device ring "
            "(buffer.device_ring=True on a 1-group mesh); keeping the "
            "host-copy path."
        )
        adopt_slabs = False

    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    warn_checkpoint_rounding(cfg, policy_steps_per_update)
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.get("checkpoint", False):
        learning_starts += start_step

    per_rank_gradient_steps = int(cfg.algo.per_rank_gradient_steps)
    ema_every = int(cfg.algo.critic.target_network_frequency) // policy_steps_per_update + 1
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    first_train_update = max(learning_starts, start_step)

    # ------------------------------------------------------------------
    # the actor–learner plane (sheeprl_tpu/plane, howto/actor_learner.md)
    # ------------------------------------------------------------------

    store_next_obs = not cfg.buffer.sample_next_obs
    slab_spec = SlabSpec.from_arrays(
        sac_slab_example(act_burst, envs_per_player, obs_dim, act_dim, store_next_obs)
    )
    scalars = {
        "num_updates": num_updates,
        "learning_starts": learning_starts,
        "first_train_update": first_train_update,
        "act_burst": act_burst,
        "max_policy_lag": int(cfg.get("plane", {}).get("max_policy_lag", 0) or 0),
    }

    actor_mirror = HostParamMirror.from_cfg(agent_state["actor"], fabric, cfg)
    root_key, player_key = jax.random.split(root_key)
    player_keys = [player_key] + [
        jax.random.fold_in(player_key, p) for p in range(1, max(num_players, 1))
    ]

    telemetry = get_telemetry()
    watchdog = telemetry.watchdog() if telemetry is not None else None
    if watchdog is not None:
        watchdog.register("sac-learner")
        watchdog.start()

    plane = build_plane(
        cfg,
        spec=slab_spec,
        entry="sheeprl_tpu.algos.sac.player:run_player",
        run_player=run_player,
        scalars=scalars,
        player_keys=player_keys,
        algo_name=cfg.algo.name,
        start_update=start_step,
        n_envs=n_envs,
        log_dir=log_dir,
        player_log_dir=log_dir if fabric.is_global_zero else None,
        thread_name="sac-player",
        initial_params=actor_mirror(agent_state["actor"]),
        watchdog=watchdog,
    )
    # sharded mode: player p's slab columns are exactly shard p's env
    # columns, so ingest routes each handle straight into its shard (one
    # copy per shard — no full-width concatenation)
    replay_plane = ReplayPlane(plane, rb) if replay_shards > 1 else None

    # ------------------------------------------------------------------
    # the learner loop (reference trainer(), :273-548): one train round per
    # policy step once learning starts
    # ------------------------------------------------------------------

    update = start_step
    try:
        while update <= num_updates:
            n_act, _random_phase = burst_plan(update, act_burst, learning_starts, num_updates)
            first, last = update, update + n_act - 1

            if watchdog is not None:
                # waiting on player trajectories is idleness, not a stall
                watchdog.pause("sac-learner")
            with span("Time/plane_wait_time", SumMetric(sync_on_compute=False), phase="plane_wait"):
                handles = [plane.recv(p, update) for p in range(plane.n_players)]
            if watchdog is not None:
                watchdog.beat("sac-learner")

            if replay_plane is not None:
                # per-shard ingest: commit-stamped adds + max-priority init
                # (the prioritized commit channel), handles released inside
                ep_stats = replay_plane.ingest(handles, n_act)
            else:
                if plane.n_players == 1:
                    rows = {k: v[:n_act] for k, v in handles[0].data.items()}
                else:
                    # assemble the full-width step rows in player order — the
                    # env axis concatenation restores the canonical seed order
                    rows = {
                        k: np.concatenate([h.data[k][:n_act] for h in handles], axis=1)
                        for k in handles[0].data
                    }
                if adopt_slabs:
                    staging.adopt_slab(rows, n_act)  # slab → HBM, one copy
                else:
                    rb.add(rows)  # the one copy of the slab→replay path
                ep_stats = [s for h in handles for s in h.ep_stats]
                for h in handles:
                    h.release()
            policy_step += n_envs * n_act

            if aggregator and not aggregator.disabled:
                for ep_rew, ep_len in ep_stats:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                    aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(f"Rank-0: policy_step={policy_step}, reward={ep_rew}")

            if last >= learning_starts and per_rank_gradient_steps > 0:
                # one gradient burst covering every update index this burst
                # collected (the reference per-step cadence for K=1),
                # including the learning-starts catch-up
                training_steps = last - max(first, learning_starts) + 1
                if first <= learning_starts <= last:
                    training_steps += learning_starts - 1
                g_total = max(training_steps, 1) * per_rank_gradient_steps
                batch = staging.sample_device(
                    world_size * cfg.per_rank_batch_size,
                    n_samples=g_total,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )

                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    root_key, train_key = jax.random.split(root_key)
                    do_ema = jnp.bool_(
                        any(u % ema_every == 0 for u in range(first, last + 1))
                    )
                    train_args = (agent_state, opt_states, batch, train_key, do_ema)
                    outs = train_fn(*train_args)
                    agent_state, opt_states, losses = outs[0], outs[1], outs[2]
                    observe_probes(
                        outs[3] if probes_enabled(cfg) and len(outs) > 3 else None,
                        step=policy_step,
                    )
                    losses = fetch_losses_if_observed(losses, aggregator)
                if needs_writeback:
                    # PER writeback (replay.strategy=td_priority): the [G, B, 1]
                    # td residuals flatten in the last plan's row order
                    staging.update_priorities(
                        np.abs(np.asarray(jax.device_get(outs[-1]))).reshape(-1)
                    )
                if telemetry is not None and telemetry.needs_train_flops():
                    # donation is off in decoupled mode; one AOT cost
                    # analysis, registered per train-step UNIT
                    register_train_cost(
                        telemetry, train_fn, *shape_specs(train_args),
                        world_size=world_size,
                    )
                train_step += world_size
                # the parameter broadcast (reference :525-529): an atomic
                # policy publication players hot-reload
                plane.publish(
                    version_after(last, first_train_update),
                    actor_mirror(agent_state["actor"]),
                )

                if aggregator and not aggregator.disabled:
                    aggregator.update("Loss/value_loss", losses[0])
                    aggregator.update("Loss/policy_loss", losses[1])
                    aggregator.update("Loss/alpha_loss", losses[2])
            elif last >= learning_starts:
                # per_rank_gradient_steps=0 skips training (sac.py contract),
                # but the version protocol must stay live or players would
                # wait forever for versions no train step will ever produce
                plane.publish(
                    version_after(last, first_train_update),
                    actor_mirror(agent_state["actor"]),
                )

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or last == num_updates
            ):
                if aggregator and not aggregator.disabled:
                    metrics_dict = aggregator.compute()
                    if logger is not None:
                        logger.log_metrics(metrics_dict, policy_step)
                    aggregator.reset()
                log_sps_metrics(
                    logger,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    last_train=last_train,
                    world_size=world_size,
                    action_repeat=cfg.env.action_repeat,
                )
                profile_tick(policy_step=policy_step, world_size=world_size)
                last_log = policy_step
                last_train = train_step

            if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
                last_checkpoint = policy_step
                ckpt_state = {
                    "agent": jax.device_get(agent_state),
                    "opt_states": jax.device_get(opt_states),
                    "update": last * world_size,
                    "batch_size": cfg.per_rank_batch_size * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
                with span("Time/checkpoint_time", phase="checkpoint"):
                    fabric.call(
                        "on_checkpoint_player",
                        ckpt_path=ckpt_path,
                        state=ckpt_state,
                        replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
                    )
                if preemption_requested():
                    # SIGTERM/SIGINT: the final checkpoint is saved; leave the
                    # loop cleanly — plane.drain() below joins the players
                    break

            update = last + 1
    finally:
        plane.drain()
        if watchdog is not None:
            watchdog.stop()
        staging.close()

    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        scale_j, bias_j = jnp.asarray(action_scale), jnp.asarray(action_bias)
        test(actor, agent_state["actor"], scale_j, bias_j, fabric, cfg, log_dir)
