"""SAC, decoupled — player/trainer split.

Behavioral contract from the reference ``sheeprl/algos/sac/sac_decoupled.py``
(main :32-60, player :63-270, trainer :273-548): a dedicated environment
process keeps the replay buffer and ships one sampled batch per policy step
to the trainers, which return updated parameters.

TPU-native design (see ``ppo/ppo_decoupled.py`` for the pattern): the player
is a CPU-host thread stepping the envs and appending to the host-side numpy
replay buffer under a lock; the trainer loop paces itself to the reference's
one-train-round-per-policy-step cadence through a step-counter condition
variable, samples directly from the shared buffer, runs the fused SPMD SAC
step, and swaps the replicated parameter pytree the player acts with.
Requires ≥2 devices like the reference.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import (
    SACActor,
    SACCritic,
    action_bounds,
    build_agent_state,
    squash_sample,
)
from sheeprl_tpu.algos.sac.sac import build_train_fn
from sheeprl_tpu.algos.sac.utils import concat_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    add_act_dispatches,
    cost_flops_of,
    get_telemetry,
    log_sps_metrics,
    shape_specs,
    span,
)
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, save_configs


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError("MineDojo is not currently supported by SAC agent")

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    if len(cfg.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.cnn_keys.encoder = []

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # vector backend picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"Provided environment: {cfg.env.id}"
            )

    act_dim = int(np.prod(action_space.shape))
    obs_dim = int(sum(np.prod(observation_space[k].shape) for k in cfg.mlp_keys.encoder))
    action_scale, action_bias = action_bounds(action_space)

    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    critic = SACCritic(hidden_size=cfg.algo.critic.hidden_size, num_critics=1)
    target_entropy = -float(act_dim)

    root_key, init_key = jax.random.split(root_key)
    agent_state = build_agent_state(
        actor, critic, init_key, int(cfg.algo.critic.n), obs_dim, act_dim, cfg.algo.alpha.alpha
    )

    qf_tx = instantiate(cfg.algo.critic.optimizer)
    actor_tx = instantiate(cfg.algo.actor.optimizer)
    alpha_tx = instantiate(cfg.algo.alpha.optimizer)
    opt_states = {
        "actor": actor_tx.init(agent_state["actor"]),
        "qf": qf_tx.init(agent_state["critics"]),
        "alpha": alpha_tx.init(agent_state["log_alpha"]),
    }

    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "opt_states": opt_states,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        opt_states = state["opt_states"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(agent_state, fabric.replicated)
    opt_states = jax.device_put(opt_states, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    buffer_size = int(cfg.buffer.size) // n_envs if not cfg.dry_run else 1
    rb = ReplayBuffer(
        max(buffer_size, 1),
        n_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{fabric.global_rank}"),
        obs_keys=("observations",),
    )
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    scale_j, bias_j = jnp.asarray(action_scale), jnp.asarray(action_bias)

    @jax.jit
    def policy_fn(actor_params, obs, key):
        mean, std = actor.apply({"params": actor_params}, obs)
        actions, _ = squash_sample(mean, std, key, scale_j, bias_j)
        return actions

    train_fn = build_train_fn(
        actor, critic, actor_tx, qf_tx, alpha_tx, cfg, fabric,
        action_scale, action_bias, target_entropy, donate=False,
    )
    batch_sharding = fabric.sharding(None, fabric.data_axis)

    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    warn_checkpoint_rounding(cfg, policy_steps_per_update)
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.get("checkpoint", False):
        learning_starts += start_step

    per_rank_gradient_steps = int(cfg.algo.per_rank_gradient_steps)
    ema_every = int(cfg.algo.critic.target_network_frequency) // policy_steps_per_update + 1

    # ------------------------------------------------------------------
    # the player thread (reference player(), :63-270): steps the envs with
    # the latest broadcast params and appends to the shared host buffer
    # ------------------------------------------------------------------

    # reentrant: the staging facade binds this same lock into the buffer's
    # add, so the player's explicit `with rb_lock` wrapper re-acquires it
    rb_lock = threading.RLock()
    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True, double-buffered host prefetch otherwise; the
    # shared lock serializes the player's adds against background sampling
    staging = make_replay_staging(
        cfg, fabric, rb, batch_sharding=batch_sharding, seed=cfg.seed, lock=rb_lock
    )
    rb = staging.rb
    step_cv = threading.Condition()
    # collected/trained counters bound the player's lead to one step (the
    # reference player blocks on the per-step param exchange, :291-294)
    progress = {"collected": start_step - 1, "trained": start_step - 1}
    actor_mirror = HostParamMirror.from_cfg(agent_state["actor"], fabric, cfg)
    param_cell = {"actor": actor_mirror(agent_state["actor"])}
    player_error: Dict[str, BaseException] = {}
    stop = threading.Event()

    # run-health: both sides of the decoupled pair heartbeat once per unit of
    # progress; the watchdog flags whichever wedges instead of the run going
    # silent on a hung env worker / device link / exchange wait
    telemetry = get_telemetry()
    watchdog = telemetry.watchdog() if telemetry is not None else None
    if watchdog is not None:
        watchdog.register("sac-player")
        watchdog.register("sac-trainer")
        watchdog.start()

    def player(player_key):
        try:
            o = envs.reset(seed=cfg.seed)[0]
            obs = concat_obs(o, cfg.mlp_keys.encoder, n_envs)
            for update in range(start_step, num_updates + 1):
                # collect step `update` while the trainer works on `update-1`
                # (one-step lead = the PPO sibling's depth-1 queue)
                if watchdog is not None:
                    # waiting for the trainer to release the next step is
                    # idleness, not a stall of the player
                    watchdog.pause("sac-player")
                with step_cv:
                    step_cv.wait_for(
                        lambda: progress["trained"] >= update - 2 or stop.is_set()
                    )
                if stop.is_set():
                    return
                if watchdog is not None:
                    watchdog.beat("sac-player")
                with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
                    if update <= learning_starts:
                        actions = envs.action_space.sample()
                    else:
                        step_key = jax.random.fold_in(player_key, update)
                        actions = np.asarray(policy_fn(param_cell["actor"], obs, step_key))
                        add_act_dispatches(1)
                    next_o, rewards, terminated, truncated, infos = envs.step(
                        actions.reshape(envs.action_space.shape)
                    )
                    dones = np.logical_or(terminated, truncated)

                ep_stats = []
                if cfg.metric.log_level > 0 and "final_info" in infos:
                    fi = infos["final_info"]
                    if isinstance(fi, dict) and "episode" in fi:
                        mask = np.asarray(fi.get("_episode", []), dtype=bool)
                        for i in np.nonzero(mask)[0]:
                            ep_stats.append(
                                (float(fi["episode"]["r"][i]), float(fi["episode"]["l"][i]))
                            )

                next_obs = concat_obs(next_o, cfg.mlp_keys.encoder, n_envs)
                real_next_obs = next_obs.copy()
                if "final_obs" in infos:
                    for idx, final_obs in enumerate(infos["final_obs"]):
                        if final_obs is not None:
                            real_next_obs[idx] = concat_obs(final_obs, cfg.mlp_keys.encoder, 1)[0]

                step_data = {
                    "observations": obs[None],
                    "actions": np.asarray(actions, np.float32).reshape(1, n_envs, -1),
                    "rewards": np.asarray(rewards, np.float32).reshape(1, n_envs, 1),
                    "dones": np.asarray(dones, np.float32).reshape(1, n_envs, 1),
                }
                if not cfg.buffer.sample_next_obs:
                    step_data["next_observations"] = real_next_obs[None]
                with rb_lock:
                    rb.add(step_data)
                obs = next_obs

                with step_cv:
                    progress["collected"] = update
                    progress.setdefault("ep_stats", []).extend(ep_stats)
                    step_cv.notify_all()
        except BaseException as e:
            player_error["error"] = e
            with step_cv:
                progress["collected"] = num_updates
                step_cv.notify_all()
        finally:
            if watchdog is not None:  # a finished player is not a stalled one
                watchdog.unregister("sac-player")

    root_key, player_key = jax.random.split(root_key)
    player_thread = threading.Thread(target=player, args=(player_key,), daemon=True, name="sac-player")
    player_thread.start()

    # ------------------------------------------------------------------
    # the trainer loop (reference trainer(), :273-548): one train round per
    # policy step once learning starts
    # ------------------------------------------------------------------

    try:
        for update in range(start_step, num_updates + 1):
            if watchdog is not None:
                # waiting for the player's next collected step is idleness,
                # not a stall of the trainer
                watchdog.pause("sac-trainer")
            with step_cv:
                step_cv.wait_for(lambda: progress["collected"] >= update)
                ep_stats = progress.pop("ep_stats", [])
            if "error" in player_error:
                raise RuntimeError("SAC player thread crashed") from player_error["error"]
            if watchdog is not None:
                watchdog.beat("sac-trainer")
            policy_step += n_envs

            if aggregator and not aggregator.disabled:
                for ep_rew, ep_len in ep_stats:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                    aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(f"Rank-0: policy_step={policy_step}, reward={ep_rew}")

            if update >= learning_starts:
                training_steps = learning_starts if update == learning_starts else 1
                g_total = max(training_steps, 1) * per_rank_gradient_steps
                # [G, B*world, ...] device arrays: ring-gathered from HBM,
                # or host-sampled + device_put overlapped with the previous
                # burst (sampling serializes on rb_lock against player adds)
                batch = staging.sample_device(
                    world_size * cfg.per_rank_batch_size,
                    n_samples=g_total,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )

                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    root_key, train_key = jax.random.split(root_key)
                    do_ema = jnp.bool_(update % ema_every == 0)
                    train_args = (agent_state, opt_states, batch, train_key, do_ema)
                    agent_state, opt_states, losses = train_fn(*train_args)
                    losses = fetch_losses_if_observed(losses, aggregator)
                if telemetry is not None and telemetry.needs_train_flops():
                    # donation is off in decoupled mode; one AOT cost
                    # analysis, registered per train-step UNIT (the counter
                    # advances by world_size per dispatched program)
                    flops = cost_flops_of(train_fn, *shape_specs(train_args))
                    telemetry.set_train_flops(flops / world_size if flops else None)
                train_step += world_size
                # parameter broadcast to the player (reference :525-529)
                param_cell["actor"] = actor_mirror(agent_state["actor"])

                if aggregator and not aggregator.disabled:
                    aggregator.update("Loss/value_loss", losses[0])
                    aggregator.update("Loss/policy_loss", losses[1])
                    aggregator.update("Loss/alpha_loss", losses[2])

            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or update == num_updates
            ):
                if aggregator and not aggregator.disabled:
                    metrics_dict = aggregator.compute()
                    if logger is not None:
                        logger.log_metrics(metrics_dict, policy_step)
                    aggregator.reset()
                log_sps_metrics(
                    logger,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    last_train=last_train,
                    world_size=world_size,
                    action_repeat=cfg.env.action_repeat,
                )
                last_log = policy_step
                last_train = train_step

            if should_checkpoint(cfg, policy_step, last_checkpoint, update, num_updates):
                last_checkpoint = policy_step
                ckpt_state = {
                    "agent": jax.device_get(agent_state),
                    "opt_states": jax.device_get(opt_states),
                    "update": update * world_size,
                    "batch_size": cfg.per_rank_batch_size * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
                with rb_lock, span("Time/checkpoint_time", phase="checkpoint"):
                    # the player must not write mid-snapshot
                    fabric.call(
                        "on_checkpoint_player",
                        ckpt_path=ckpt_path,
                        state=ckpt_state,
                        replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
                    )
                if preemption_requested():
                    # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                    # drains the in-flight write) — leave the train loop cleanly
                    break

            # release the player for the next step (bounded one-step lead)
            with step_cv:
                progress["trained"] = update
                step_cv.notify_all()
    finally:
        stop.set()
        with step_cv:
            step_cv.notify_all()
        player_thread.join(timeout=30)
        if watchdog is not None:
            watchdog.stop()
        staging.close()
        envs.close()

    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(actor, agent_state["actor"], scale_j, bias_j, fabric, cfg, log_dir)
