"""SAC evaluation entrypoint (reference ``sheeprl/algos/sac/evaluate.py``)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor, action_bounds
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_evaluation(algorithms=["sac"])
def evaluate_sac(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))

    env = make_eval_env(cfg, log_dir)
    action_space = env.action_space
    observation_space = env.observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    env.close()

    act_dim = int(np.prod(action_space.shape))
    action_scale, action_bias = action_bounds(action_space)
    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    actor_params = params_on_device(state["agent"]["actor"])
    test(actor, actor_params, jnp.asarray(action_scale), jnp.asarray(action_bias), fabric, cfg, log_dir)


@register_evaluation(algorithms=["sac_decoupled"])
def evaluate_sac_decoupled(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    evaluate_sac(fabric, cfg, state)
