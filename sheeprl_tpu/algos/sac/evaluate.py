"""SAC evaluation (reference ``sheeprl/algos/sac/evaluate.py``), collapsed
onto the shared eval service: this file only knows how to rebuild the frozen
actor and act greedily on a batch; episode running, artifacts and registry
appends live in :mod:`sheeprl_tpu.evals.service`."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor, action_bounds, greedy_action
from sheeprl_tpu.algos.sac.utils import concat_obs
from sheeprl_tpu.evals.service import EvalPolicy, register_eval_builder, run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


# droq and sac_decoupled train the same SACActor with the same checkpoint
# layout, so one builder serves all three.
@register_eval_builder(algorithms=["sac", "sac_decoupled", "droq"])
def sac_eval_policy(fabric, cfg, state, observation_space, action_space) -> EvalPolicy:
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")

    act_dim = int(np.prod(action_space.shape))
    action_scale, action_bias = action_bounds(action_space)
    scale = jnp.asarray(action_scale)
    bias = jnp.asarray(action_bias)
    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    actor_params = params_on_device(state["agent"]["actor"])
    mlp_keys = list(cfg.mlp_keys.encoder)

    @jax.jit
    def _act(params, obs):
        mean, _ = actor.apply({"params": params}, obs)
        return greedy_action(mean, scale, bias)

    def act(obs, policy_state, key):
        n = int(np.asarray(next(iter(obs.values()))).shape[0])
        return np.asarray(_act(actor_params, concat_obs(obs, mlp_keys, n))), policy_state

    return EvalPolicy(act=act)


@register_evaluation(algorithms=["sac"])
def evaluate_sac(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)


@register_evaluation(algorithms=["sac_decoupled"])
def evaluate_sac_decoupled(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
