"""SAC losses (reference ``sheeprl/algos/sac/loss.py``, Eqs. 5/7/17 of
https://arxiv.org/abs/1812.05905), pure jnp."""

from __future__ import annotations

import jax.numpy as jnp


def policy_loss(alpha: jnp.ndarray, logprobs: jnp.ndarray, qf_values: jnp.ndarray) -> jnp.ndarray:
    # Eq. 7
    return ((alpha * logprobs) - qf_values).mean()


def critic_loss(qf_values: jnp.ndarray, next_qf_value: jnp.ndarray, num_critics: int) -> jnp.ndarray:
    # Eq. 5 — sum of per-critic MSEs against the shared TD target
    return sum(
        ((qf_values[..., i : i + 1] - next_qf_value) ** 2).mean() for i in range(num_critics)
    )


def entropy_loss(log_alpha: jnp.ndarray, logprobs: jnp.ndarray, target_entropy: jnp.ndarray) -> jnp.ndarray:
    # Eq. 17
    return (-log_alpha * (logprobs + target_entropy)).mean()
