"""SAC, coupled — off-policy continuous control.

Behavioral contract from the reference ``sheeprl/algos/sac/sac.py``
(train :33-81, main :84-398): vector observations only, twin-Q ensemble with
EMA targets, tanh-Gaussian actor, alpha autotuning against
``target_entropy = -act_dim``; per update one env step, then
``per_rank_gradient_steps`` SGD batches sampled from the replay buffer (with a
catch-up burst of ``learning_starts`` batches on the first training update).

TPU-native design:

- The reference's per-update pipeline (sample → all_gather → DistributedSampler
  → per-batch train() with three backward/allreduce passes) is ONE jitted
  ``shard_map`` program: the host samples ``G×B×world`` transitions, ships
  them sharded over the mesh, and the device scans over G gradient steps —
  critic, actor, and alpha updates each with ``pmean``-ed grads, plus the
  conditional target-EMA folded in as a ``jnp.where`` on the parameter trees.
- Collection goes through the rollout engine (``envs/rollout``,
  ``howto/rollout_engine.md``): with ``env.backend=jax`` the whole burst —
  act, env step, auto-reset, device-ring add — is one ``lax.scan`` under
  jit (zero host involvement); on the Python backend the acting loop body
  lives in a host callback that a ``BurstActor`` scans ``env.act_burst``
  times per device dispatch (K=1 = the exact per-step reference path), and
  one train program covers the burst's gradient steps.
- The critic ensemble is vmapped stacked params (see ``agent.py``) — the
  twin-Q min and per-critic MSE sum are single batched ops.
- The whole agent state (actor/critics/targets/log_alpha + 3 optimizer
  states) is one pytree: replication, donation, and checkpointing are
  single calls.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.sac.agent import (
    SACActor,
    SACCritic,
    action_bounds,
    build_agent_state,
    ensemble_q,
    greedy_action,
    squash_sample,
)
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import concat_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.device_ring import DeviceRingTransitions
from sheeprl_tpu.data.staging import RingStaging, make_replay_staging
from sheeprl_tpu.envs.rollout import BurstActor, JaxRolloutEngine, make_jax_env
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.envs.vector.factory import resolve_backend
from sheeprl_tpu.evals.inrun import maybe_start_inrun_eval
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    get_telemetry,
    learn_probes,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    register_train_cost,
    set_shard_footprint,
    shape_specs,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of, get_lr, set_lr
from sheeprl_tpu.parallel.shard import measured_bytes_per_device
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, save_configs
from sheeprl_tpu.utils.jax_compat import shard_map


def build_train_fn(
    actor: SACActor,
    critic: SACCritic,
    actor_tx,
    qf_tx,
    alpha_tx,
    cfg,
    fabric,
    action_scale: np.ndarray,
    action_bias: np.ndarray,
    target_entropy: float,
    donate: bool = True,
    state_plan=None,
    opt_plan=None,
    emit_td: bool = False,
):
    """Compile G gradient steps (critic → EMA → actor → alpha) as one SPMD
    program. ``batch`` leaves are ``[G, B_local, ...]``; ``do_ema`` is a
    dynamic bool so the EMA cadence never recompiles.

    ``state_plan``/``opt_plan`` (from ``fabric.shard_plan`` over the agent
    state and optimizer-state trees) switch the program onto the
    ``{'data','model'}`` mesh as ONE GSPMD program: no manual shard_map
    region (``axis=None`` makes the per-shard gradient pmean an identity —
    the loss spans the global batch, so its gradient is already the
    all-reduced one), params/opt state enter via ``in_shardings``/
    ``out_shardings`` with the plans' model-axis specs, and XLA inserts all
    collectives. The jax-0.4-era partitioner CHECK-fails on ``lax.scan``
    inside a partially-manual (``auto=``) shard_map, so the sharded path
    avoids shard_map entirely. ``None`` is the byte-identical manual
    data-parallel program.

    ``emit_td=True`` (the prioritized-replay writeback path,
    ``replay.strategy=td_priority``) additionally returns the per-row TD
    residual ``min_i Q_i(s,a) − y`` of the *pre-update* critics, stacked
    ``[G, B, 1]`` in the staged batch's row order, as the LAST output — the
    aux of the same critic-loss evaluation, so the extra cost is one output,
    not a second forward pass. With ``emit_td=False`` (the default, and
    every uniform-replay path) the built program is byte-identical to
    before the flag existed."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    n_critics = int(cfg.algo.critic.n)
    data_axis = fabric.data_axis
    axis = data_axis if state_plan is None else None
    scale = jnp.asarray(action_scale)
    bias = jnp.asarray(action_bias)
    tgt_entropy = jnp.float32(target_entropy)
    # learning-health probes (obs/learn): build-time gate — with the sentinel
    # uninstalled the program carries zero probe ops and its outputs (and
    # params) are bitwise those of a probes-off build
    learn_on = probes_enabled(cfg)
    learn_clips = {
        "actor": clip_norm_of(actor_tx),
        "critic": clip_norm_of(qf_tx),
        "alpha": clip_norm_of(alpha_tx),
    }

    def one_step(carry, batch_and_key):
        state, opt_states, do_ema = carry
        batch, key = batch_and_key
        a_key, c_key = jax.random.split(key)

        # ---- critic update (reference train :47-55)
        alpha = jax.lax.stop_gradient(jnp.exp(state["log_alpha"]))
        next_mean, next_std = actor.apply({"params": state["actor"]}, batch["next_observations"])
        next_actions, next_logprob = squash_sample(next_mean, next_std, c_key, scale, bias)
        target_q = ensemble_q(critic, state["target_critics"], batch["next_observations"], next_actions)
        min_target = jnp.min(target_q, axis=-1, keepdims=True) - alpha * next_logprob
        td_target = batch["rewards"] + (1.0 - batch["dones"]) * gamma * min_target
        td_target = jax.lax.stop_gradient(td_target)

        if emit_td:

            def qf_loss_td_fn(critic_params):
                q = ensemble_q(critic, critic_params, batch["observations"], batch["actions"])
                return critic_loss(q, td_target, n_critics), q

            (qf_loss, q_pre), qf_grads = jax.value_and_grad(qf_loss_td_fn, has_aux=True)(
                state["critics"]
            )
            td = jnp.min(q_pre, axis=-1, keepdims=True) - td_target
        else:

            def qf_loss_fn(critic_params):
                q = ensemble_q(critic, critic_params, batch["observations"], batch["actions"])
                return critic_loss(q, td_target, n_critics)

            qf_loss, qf_grads = jax.value_and_grad(qf_loss_fn)(state["critics"])
            td = None
        qf_grads = pmean(qf_grads, axis)
        qf_updates, qf_opt = qf_tx.update(qf_grads, opt_states["qf"], state["critics"])
        critics = optax.apply_updates(state["critics"], qf_updates)

        # ---- target EMA (reference train :57-59), gated without recompiling
        ema = jax.tree_util.tree_map(
            lambda p, t: tau * p + (1.0 - tau) * t, critics, state["target_critics"]
        )
        targets = jax.tree_util.tree_map(
            lambda e, t: jnp.where(do_ema, e, t), ema, state["target_critics"]
        )

        # ---- actor update (reference train :61-68), against the fresh critics
        def actor_loss_fn(actor_params):
            mean, std = actor.apply({"params": actor_params}, batch["observations"])
            actions, logprob = squash_sample(mean, std, a_key, scale, bias)
            q = ensemble_q(critic, critics, batch["observations"], actions)
            min_q = jnp.min(q, axis=-1, keepdims=True)
            return policy_loss(alpha, logprob, min_q), logprob

        (actor_loss, logprob), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            state["actor"]
        )
        actor_grads = pmean(actor_grads, axis)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states["actor"], state["actor"])
        actor_params = optax.apply_updates(state["actor"], actor_updates)

        # ---- alpha update (reference train :70-75; grad all-reduced)
        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, jax.lax.stop_gradient(logprob), tgt_entropy)

        alpha_loss, alpha_grad = jax.value_and_grad(alpha_loss_fn)(state["log_alpha"])
        alpha_grad = pmean(alpha_grad, axis)
        alpha_updates, alpha_opt = alpha_tx.update(alpha_grad, opt_states["alpha"], state["log_alpha"])
        log_alpha = optax.apply_updates(state["log_alpha"], alpha_updates)

        new_state = {
            "actor": actor_params,
            "critics": critics,
            "target_critics": targets,
            "log_alpha": log_alpha,
        }
        new_opts = {"actor": actor_opt, "qf": qf_opt, "alpha": alpha_opt}
        metrics = jnp.stack([qf_loss, actor_loss, alpha_loss])
        if learn_on:
            # grads are already pmean'd above, so every shard computes the
            # identical probe values — no extra collective needed
            probes = learn_probes(
                {
                    "actor": actor_grads,
                    "critic": qf_grads,
                    "alpha": alpha_grad,
                },
                params={
                    "actor": state["actor"],
                    "critic": state["critics"],
                    "alpha": state["log_alpha"],
                },
                updates={
                    "actor": actor_updates,
                    "critic": qf_updates,
                    "alpha": alpha_updates,
                },
                losses=(qf_loss, actor_loss, alpha_loss),
                clip_norms=learn_clips,
            )
            ys = (metrics, probes, td) if emit_td else (metrics, probes)
            return (new_state, new_opts, do_ema), ys
        if emit_td:
            return (new_state, new_opts, do_ema), (metrics, td)
        return (new_state, new_opts, do_ema), metrics

    def local_train(state, opt_states, batch, key, do_ema):
        g = jax.tree_util.tree_leaves(batch)[0].shape[0]
        keys = jax.random.split(key, g)
        (state, opt_states, _), ys = jax.lax.scan(
            one_step, (state, opt_states, do_ema), (batch, keys)
        )
        td = None
        if learn_on and emit_td:
            metrics, probes, td = ys
        elif learn_on:
            metrics, probes = ys
        elif emit_td:
            metrics, td = ys
            probes = None
        else:
            metrics, probes = ys, None
        metrics = pmean(jnp.mean(metrics, axis=0), axis)
        out = (state, opt_states, metrics)
        if learn_on:
            # probes ride the scan ys stacked [G]: per-gradient-step samples
            out = out + (probes,)
        if emit_td:
            # td residuals ride the same ys, stacked [G, B, 1] — always LAST
            out = out + (td,)
        return out

    # decoupled mode keeps the old actor params alive for the player
    # thread, so donation must be off there
    donate_argnums = (0, 1) if donate else ()
    n_learn = 1 if learn_on else 0
    # td residuals are [G, B, 1] with the batch axis data-sharded, like the
    # staged batch itself
    td_specs = (P(None, data_axis),) if emit_td else ()
    if state_plan is None:
        shmapped = shard_map(
            local_train,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P(None, data_axis), P(), P()),
            out_specs=(P(), P(), P()) + (P(),) * n_learn + td_specs,
            check_vma=False,
        )
        return jax.jit(shmapped, donate_argnums=donate_argnums)
    rep = fabric.replicated
    td_shardings = (fabric.sharding(None, data_axis),) if emit_td else ()
    return jax.jit(
        local_train,
        in_shardings=(
            state_plan.shardings(),
            opt_plan.shardings(),
            fabric.sharding(None, data_axis),
            rep,
            rep,
        ),
        out_shardings=(state_plan.shardings(), opt_plan.shardings(), rep)
        + (rep,) * n_learn
        + td_shardings,
        donate_argnums=donate_argnums,
    )


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError("MineDojo is not currently supported by SAC agent")

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    if len(cfg.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.cnn_keys.encoder = []

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # execution plane picked by env.backend (envs/vector/factory.py): the
    # Python vector-env plane, or the pure-JAX rollout engine (tier a) where
    # whole collection bursts run on device (howto/rollout_engine.md)
    backend = resolve_backend(cfg)
    envs = None
    jax_env = None
    if backend == "jax":
        if world_size > 1:
            raise ValueError(
                "env.backend=jax currently supports single-device SAC runs "
                "(the jitted-scan collection owns one device's ring shard); "
                f"got fabric world_size={world_size}"
            )
        jax_env = make_jax_env(cfg.env.id, cfg.env.max_episode_steps)
        action_space = jax_env.action_space
        observation_space = jax_env.observation_space
    else:
        # vector backend picked by env.vectorization (envs/vector/factory.py)
        envs = make_vector_env(cfg, fabric, log_dir)
        action_space = envs.single_action_space
        observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"Provided environment: {cfg.env.id}"
            )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)

    act_dim = int(np.prod(action_space.shape))
    obs_dim = int(sum(np.prod(observation_space[k].shape) for k in cfg.mlp_keys.encoder))
    action_scale, action_bias = action_bounds(action_space)

    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    critic = SACCritic(hidden_size=cfg.algo.critic.hidden_size, num_critics=1)
    target_entropy = -float(act_dim)

    root_key, init_key = jax.random.split(root_key)
    agent_state = build_agent_state(
        actor, critic, init_key, int(cfg.algo.critic.n), obs_dim, act_dim, cfg.algo.alpha.alpha
    )

    qf_tx = instantiate(cfg.algo.critic.optimizer)
    actor_tx = instantiate(cfg.algo.actor.optimizer)
    alpha_tx = instantiate(cfg.algo.alpha.optimizer)
    opt_states = {
        "actor": actor_tx.init(agent_state["actor"]),
        "qf": qf_tx.init(agent_state["critics"]),
        "alpha": alpha_tx.init(agent_state["log_alpha"]),
    }

    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "opt_states": opt_states,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        opt_states = state["opt_states"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    # Parameter sharding (parallel.model_axis>1): spec-assign params and
    # optimizer state over the 'model' axis and place them sharded. Resumed
    # checkpoints arrive as full host arrays, so restoring onto a different
    # model_axis than they were saved under is the same respec-and-reshard
    # path. model_axis=1 keeps the replicated placement untouched.
    state_plan = fabric.shard_plan(agent_state)
    opt_plan = fabric.shard_plan(opt_states)
    if state_plan is None:
        agent_state = jax.device_put(agent_state, fabric.replicated)
        opt_states = jax.device_put(opt_states, fabric.replicated)
    else:
        agent_state = state_plan.place(agent_state)
        opt_states = opt_plan.place(opt_states)
    set_shard_footprint(
        measured_bytes_per_device(agent_state),
        measured_bytes_per_device(opt_states),
        fabric.model_axis_size,
    )

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=("observations",),
        dry_run_size=1,
    )

    # ------------------------------------------------------------------
    # jitted programs
    # ------------------------------------------------------------------

    scale_j, bias_j = jnp.asarray(action_scale), jnp.asarray(action_bias)

    actor_mirror = HostParamMirror.from_cfg(agent_state["actor"], fabric, cfg)
    play_actor = actor_mirror(agent_state["actor"])

    # in-run eval (howto/evaluation.md): rank 0 publishes the actor through
    # the policy channel every eval.every_n_steps; a separate process scores
    # it, so nothing below touches the train-step critical path
    inrun = maybe_start_inrun_eval(fabric, cfg, log_dir)

    needs_writeback = bool(getattr(rb, "needs_writeback", False))
    train_fn = build_train_fn(
        actor, critic, actor_tx, qf_tx, alpha_tx, cfg, fabric, action_scale, action_bias, target_entropy,
        state_plan=state_plan, opt_plan=opt_plan, emit_td=needs_writeback,
    )
    batch_sharding = fabric.sharding(None, fabric.data_axis)
    if backend == "jax" and hasattr(rb, "plan_burst"):
        raise ValueError(
            "env.backend=jax collects straight into the device ring, which "
            "needs the plain replay buffer — run prioritized/sharded replay "
            "(replay.strategy/replay.shards) on the python backend"
        )
    if backend == "jax":
        # the jitted-scan collection writes straight into the device ring —
        # the ring IS the collection target on this backend, so it is always
        # on regardless of buffer.device_ring
        if not cfg.buffer.get("device_ring", False):
            warnings.warn(
                "env.backend=jax collects straight into the device ring; "
                "enabling it (buffer.device_ring was off)"
            )
        ring = DeviceRingTransitions(
            rb, device=getattr(fabric, "device", None), seed=cfg.seed
        )
        staging = RingStaging(ring)
        rb = ring
    else:
        # TPU-first replay staging (data/staging.py): device-ring gathers when
        # buffer.device_ring=True, double-buffered host prefetch otherwise
        staging = make_replay_staging(
            cfg, fabric, rb, batch_sharding=batch_sharding, seed=cfg.seed
        )
        rb = staging.rb

    if backend == "jax":
        # tier (a): act -> step -> ring-add inside one lax.scan under jit
        def engine_policy(actor_params, e_obs, key):
            mean, std = actor.apply({"params": actor_params}, e_obs)
            actions, _ = squash_sample(mean, std, key, scale_j, bias_j)
            return actions

        root_key, engine_key = jax.random.split(root_key)
        engine = JaxRolloutEngine(
            jax_env,
            n_envs,
            engine_key,
            policy=engine_policy,
            ring=rb,
            store_next_obs=not cfg.buffer.sample_next_obs,
        )

    # Global counters (reference sac.py:206-215)
    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.get("checkpoint", False):
        learning_starts += start_step

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update}), so "
            "the metrics will be logged at the nearest greater multiple of the "
            "policy_steps_per_update value."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    if backend == "python":
        o = envs.reset(seed=cfg.seed)[0]
        obs = concat_obs(o, cfg.mlp_keys.encoder, n_envs)
        root_key, play_key = jax.random.split(root_key)
        play_key = actor_mirror.put_key(play_key)

    per_rank_gradient_steps = int(cfg.algo.per_rank_gradient_steps)
    ema_every = int(cfg.algo.critic.target_network_frequency) // policy_steps_per_update + 1
    # fault injection (metric.telemetry.learn.inject_lr_spike_*): multiply
    # every optimizer's LR once at the configured update — drives the
    # divergence-sentinel acceptance tests, never enabled in a real run
    lr_spike_at = None
    lr_spike_factor = 0.0
    try:
        _lcfg = (cfg.metric.get("telemetry", {}) or {}).get("learn", {}) or {}
        if _lcfg.get("inject_lr_spike_at") is not None:
            lr_spike_at = int(_lcfg["inject_lr_spike_at"])
            lr_spike_factor = float(_lcfg.get("inject_lr_spike_factor", 0) or 0)
    except AttributeError:
        pass
    # burst acting (tier b, howto/rollout_engine.md): K env steps per device
    # dispatch; 1 reproduces the per-step path exactly
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)

    if backend == "python":
        # The acting loop body as one host function: env step (against the
        # PR-5 vector plane), SAME_STEP final_obs fixup, episode logging,
        # buffer add — the old per-step block verbatim. The BurstActor scans
        # it K times per dispatch through an ordered io_callback; the random
        # prefill phase calls it directly (no policy, no dispatch at all).
        state_box = {"obs": obs, "policy_step": policy_step}

        def _host_env_step(actions):
            actions = np.asarray(actions)
            state_box["policy_step"] += n_envs
            with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
                next_o, rewards, terminated, truncated, infos = envs.step(
                    actions.reshape(envs.action_space.shape)
                )
            dones = np.logical_or(terminated, truncated)

            if cfg.metric.log_level > 0 and "final_info" in infos:
                fi = infos["final_info"]
                if isinstance(fi, dict) and "episode" in fi:
                    mask = np.asarray(fi.get("_episode", []), dtype=bool)
                    for i in np.nonzero(mask)[0]:
                        ep_rew = float(fi["episode"]["r"][i])
                        ep_len = float(fi["episode"]["l"][i])
                        if aggregator and not aggregator.disabled:
                            aggregator.update("Rewards/rew_avg", ep_rew)
                            aggregator.update("Game/ep_len_avg", ep_len)
                        fabric.print(
                            f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                        )

            # Real next obs: under SAME_STEP autoreset the terminal obs lands
            # in final_obs while next_o holds the reset obs (reference
            # sac.py:268-274)
            next_obs = concat_obs(next_o, cfg.mlp_keys.encoder, n_envs)
            real_next_obs = next_obs.copy()
            if "final_obs" in infos:
                for idx, final_obs in enumerate(infos["final_obs"]):
                    if final_obs is not None:
                        real_next_obs[idx] = concat_obs(final_obs, cfg.mlp_keys.encoder, 1)[0]

            step_data = {
                "observations": state_box["obs"][None],
                "actions": np.asarray(actions, np.float32).reshape(1, n_envs, -1),
                "rewards": np.asarray(rewards, np.float32).reshape(1, n_envs, 1),
                "dones": np.asarray(dones, np.float32).reshape(1, n_envs, 1),
            }
            if not cfg.buffer.sample_next_obs:
                step_data["next_observations"] = real_next_obs[None]
            rb.add(step_data)
            state_box["obs"] = next_obs
            return next_obs

        def _act_fn(actor_params, a_obs, key):
            # key advances inside the jitted burst: same discipline as the
            # old per-step policy_fn, so K=1 is bitwise the per-step path
            key, sub = jax.random.split(key)
            mean, std = actor.apply({"params": actor_params}, a_obs)
            actions, _ = squash_sample(mean, std, sub, scale_j, bias_j)
            return (actions,), key

        burst_actor = BurstActor(_act_fn, _host_env_step, obs)

    update = start_step
    while update <= num_updates:
        if backend == "jax":
            # tier (a): the whole burst (act, step, auto-reset, ring add)
            # is ONE device program; random bursts clamp at the
            # learning-starts boundary so the catch-up train runs on time
            # (and at num_updates, so learning_starts > num_updates can't
            # collect past total_steps or skip the final log/ckpt gates)
            random_phase = update <= learning_starts
            boundary = min(learning_starts, num_updates) if random_phase else num_updates
            n_act = max(min(act_burst, boundary - update + 1), 1)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                stats = engine.collect(
                    agent_state["actor"], n_act, random_actions=random_phase
                )
            if cfg.metric.log_level > 0:
                _, done_b, ep_ret_b, ep_len_b = (np.asarray(s) for s in stats)
                for t_i, env_i in zip(*np.nonzero(done_b)):
                    ep_rew = float(ep_ret_b[t_i, env_i])
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                        aggregator.update("Game/ep_len_avg", float(ep_len_b[t_i, env_i]))
                    fabric.print(
                        f"Rank-0: policy_step={policy_step + (int(t_i) + 1) * n_envs}, "
                        f"reward_env_{int(env_i)}={ep_rew}"
                    )
            policy_step += n_envs * n_act
        elif update <= learning_starts:
            n_act = 1
            _host_env_step(envs.action_space.sample())
            policy_step = state_box["policy_step"]
        else:
            n_act = max(min(act_burst, num_updates - update + 1), 1)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, play_key = burst_actor.rollout(
                    play_actor, state_box["obs"], play_key, n_act
                )
            policy_step = state_box["policy_step"]

        first = update
        update += n_act
        last = update - 1

        if last >= learning_starts and per_rank_gradient_steps > 0:
            # one gradient burst covering every update index this burst
            # collected (the reference per-step cadence for K=1; K>1 trades
            # interleaving granularity for one dispatch per K steps)
            training_steps = last - max(first, learning_starts) + 1
            if first <= learning_starts <= last:
                # the catch-up burst the reference runs at learning_starts
                training_steps += learning_starts - 1
            g_total = training_steps * per_rank_gradient_steps
            # [G, B*world, ...] device arrays: ring-gathered from HBM, or
            # host-sampled + device_put overlapped with the previous burst
            batch = staging.sample_device(
                world_size * cfg.per_rank_batch_size,
                n_samples=g_total,
                sample_next_obs=cfg.buffer.sample_next_obs,
            )

            telemetry = get_telemetry()
            train_specs = None
            with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                root_key, train_key = jax.random.split(root_key)
                # EMA cadence: fires when any update index covered by this
                # burst hits it (K=1 reduces to the reference per-update gate)
                do_ema = jnp.bool_(
                    any(u % ema_every == 0 for u in range(first, last + 1))
                )
                if lr_spike_at is not None and lr_spike_factor and first <= lr_spike_at <= last:
                    lr_spike_at = None  # fires exactly once
                    opt_states = {
                        k: set_lr(v, jnp.float32(get_lr(v) * lr_spike_factor))
                        for k, v in opt_states.items()
                    }
                train_args = (agent_state, opt_states, batch, train_key, do_ema)
                if telemetry is not None and telemetry.needs_train_flops():
                    # specs captured pre-call: the train step donates its state
                    train_specs = shape_specs(train_args)
                outs = train_fn(*train_args)
                agent_state, opt_states, losses = outs[0], outs[1], outs[2]
                # [G]-stacked learn probes (4th output when probes are on):
                # one cadence-gated device_get inside observe_probes
                observe_probes(
                    outs[3] if probes_enabled(cfg) and len(outs) > 3 else None,
                    step=policy_step,
                )
                losses = fetch_losses_if_observed(losses, aggregator)
            if needs_writeback:
                # PER writeback (replay.strategy=td_priority): the [G, B, 1]
                # td residuals flatten in the last plan's row order
                staging.update_priorities(
                    np.abs(np.asarray(jax.device_get(outs[-1]))).reshape(-1)
                )
            if train_specs is not None:
                # per train-step UNIT (FLOPs + bytes accessed): the counter
                # advances by world_size per dispatched program (which runs
                # g_total gradient steps)
                register_train_cost(
                    telemetry, train_fn, *train_specs, world_size=world_size
                )
            if backend == "python":
                play_actor = actor_mirror(agent_state["actor"])
            train_step += world_size

            if aggregator and not aggregator.disabled:
                aggregator.update("Loss/value_loss", losses[0])
                aggregator.update("Loss/policy_loss", losses[1])
                aggregator.update("Loss/alpha_loss", losses[2])

        if inrun is not None and last >= learning_starts and inrun.due(policy_step):
            # versioned by policy_step; the npz write runs on the publisher's
            # writer thread, so the cost here is one actor-sized device_get
            inrun.maybe_publish(
                policy_step, {"agent": {"actor": jax.device_get(agent_state["actor"])}}
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "opt_states": jax.device_get(opt_states),
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            with span("Time/checkpoint_time", phase="checkpoint"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
                    sharding_meta=state_plan.describe() if state_plan is not None else None,
                )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    if inrun is not None:
        inrun.close()
    staging.close()
    if envs is not None:
        envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        if backend == "jax":
            # evaluation runs the GYMNASIUM env of the same id (a dynamics
            # parity statement for the native envs) — pure-JAX-only ids
            # (brax/*) have no gymnasium counterpart, so a failed eval must
            # not crash the completed training run
            try:
                test(actor, agent_state["actor"], scale_j, bias_j, fabric, cfg, log_dir)
            except Exception as exc:
                warnings.warn(
                    f"run_test skipped for env.backend=jax: the evaluation "
                    f"env {cfg.env.id!r} could not be built/run through the "
                    f"gymnasium pipeline ({exc!r}); set algo.run_test=False "
                    "for pure-JAX-only envs"
                )
        else:
            test(actor, agent_state["actor"], scale_j, bias_j, fabric, cfg, log_dir)
