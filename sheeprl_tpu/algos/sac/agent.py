"""SAC agent: flax modules + pure sampling math + vmapped critic ensemble.

Behavioral contract from the reference ``sheeprl/algos/sac/agent.py``
(SACCritic :16-50, SACActor :53-152, SACAgent :155-271): tanh-squashed
Gaussian actor with the Eq.-26 log-prob correction and action rescaling to the
env bounds; N twin critics with EMA target copies; learnable ``log_alpha``
with ``target_entropy = -act_dim``.

TPU-native differences:

- The critic ensemble is ONE module with **stacked parameters** applied under
  ``jax.vmap`` — the N small Q-networks become one batched matmul stack on the
  MXU instead of N sequential kernel launches (reference loops over
  ``self.qfs`` modules).
- Target networks are plain parameter pytrees; the EMA update is a
  ``tree_map`` inside the jitted train step (reference mutates
  ``.data`` tensors under ``no_grad``).
- All agent state (actor/critic/target params + log_alpha) lives in one dict
  pytree so checkpointing and replication are single calls.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import MLP

LOG_STD_MAX = 2.0
LOG_STD_MIN = -5.0


class SACActor(nn.Module):
    """MLP trunk + (mean, log_std) heads (reference SACActor :53-107)."""

    action_dim: int
    hidden_size: int = 256

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu")(obs)
        mean = nn.Dense(self.action_dim)(x)
        log_std = nn.Dense(self.action_dim)(x)
        std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
        return mean, std


class SACCritic(nn.Module):
    """Q(s, a) MLP (reference SACCritic :16-50); applied under vmap over a
    stacked-parameter ensemble axis."""

    hidden_size: int = 256
    num_critics: int = 1

    @nn.compact
    def __call__(self, obs: jnp.ndarray, action: jnp.ndarray) -> jnp.ndarray:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=self.num_critics,
            activation="relu",
        )(x)


# ---------------------------------------------------------------------------
# pure sampling math (reference get_actions_and_log_probs :108-138)
# ---------------------------------------------------------------------------


def squash_sample(
    mean: jnp.ndarray,
    std: jnp.ndarray,
    key: jax.Array,
    action_scale: jnp.ndarray,
    action_bias: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reparameterized tanh-Gaussian sample rescaled to env bounds, with the
    Eq.-26 change-of-variable log-prob (summed over action dims, keepdim)."""
    x_t = mean + std * jax.random.normal(key, mean.shape, dtype=mean.dtype)
    y_t = jnp.tanh(x_t)
    action = y_t * action_scale + action_bias
    log_prob = _normal_log_prob(x_t, mean, std)
    log_prob -= jnp.log(action_scale * (1.0 - y_t**2) + 1e-6)
    return action, log_prob.sum(-1, keepdims=True)


def greedy_action(
    mean: jnp.ndarray, action_scale: jnp.ndarray, action_bias: jnp.ndarray
) -> jnp.ndarray:
    """Deterministic policy output (reference get_greedy_actions :140-152)."""
    return jnp.tanh(mean) * action_scale + action_bias


def _normal_log_prob(x: jnp.ndarray, mean: jnp.ndarray, std: jnp.ndarray) -> jnp.ndarray:
    return -((x - mean) ** 2) / (2 * std**2) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)


# ---------------------------------------------------------------------------
# ensemble helpers
# ---------------------------------------------------------------------------


def init_critic_ensemble(
    critic: SACCritic, key: jax.Array, n: int, obs_dim: int, act_dim: int
) -> Any:
    """Stacked params for ``n`` independent critics (leading ensemble axis)."""
    dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: critic.init(k, dummy_obs, dummy_act)["params"])(keys)


def ensemble_q(critic: SACCritic, stacked_params: Any, obs: jnp.ndarray, action: jnp.ndarray) -> jnp.ndarray:
    """Apply the ensemble → ``[batch, n_critics]`` (reference get_q_values :257)."""
    q = jax.vmap(lambda p: critic.apply({"params": p}, obs, action))(stacked_params)
    # [n, batch, 1] → [batch, n]
    return jnp.moveaxis(q[..., 0], 0, -1)


def build_agent_state(
    actor: SACActor,
    critic: SACCritic,
    key: jax.Array,
    n_critics: int,
    obs_dim: int,
    act_dim: int,
    alpha: float,
) -> Dict[str, Any]:
    """One pytree holding every learnable/derived parameter of the agent."""
    a_key, c_key = jax.random.split(key)
    actor_params = actor.init(a_key, jnp.zeros((1, obs_dim), jnp.float32))["params"]
    critic_params = init_critic_ensemble(critic, c_key, n_critics, obs_dim, act_dim)
    return {
        "actor": actor_params,
        "critics": critic_params,
        "target_critics": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([alpha], jnp.float32)),
    }


def action_bounds(action_space) -> Tuple[np.ndarray, np.ndarray]:
    """(scale, bias) from the env action bounds (reference buffers :86-88).
    Unbounded dims fall back to scale 1 / bias 0 (tanh range) so the
    squashed log-prob stays finite."""
    low = np.asarray(action_space.low, np.float32).reshape(-1)
    high = np.asarray(action_space.high, np.float32).reshape(-1)
    unbounded = ~(np.isfinite(low) & np.isfinite(high))
    low = np.where(unbounded, -1.0, low)
    high = np.where(unbounded, 1.0, high)
    scale = (high - low) / 2.0
    bias = (high + low) / 2.0
    return scale.astype(np.float32), bias.astype(np.float32)
