"""SAC helpers (reference ``sheeprl/algos/sac/utils.py``)."""

from __future__ import annotations

from typing import Dict

import jax
import numpy as np

from sheeprl_tpu.envs.vector import make_eval_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}


def concat_obs(obs: Dict[str, np.ndarray], mlp_keys, n_envs: int) -> np.ndarray:
    """Stack the selected vector keys into one float32 ``[n_envs, obs_dim]``."""
    return np.concatenate(
        [np.asarray(obs[k], np.float32).reshape(n_envs, -1) for k in mlp_keys], axis=-1
    )


def test(actor, actor_params, action_scale, action_bias, fabric, cfg, log_dir: str) -> None:
    """Greedy single-env evaluation episode (reference utils.py:19-46)."""
    from sheeprl_tpu.algos.sac.agent import greedy_action

    env = make_eval_env(cfg, log_dir)

    @jax.jit
    def act(params, obs):
        mean, _ = actor.apply({"params": params}, obs)
        return greedy_action(mean, action_scale, action_bias)

    done = False
    cumulative_rew = 0.0
    o = env.reset(seed=cfg.seed)[0]
    while not done:
        obs = concat_obs(o, cfg.mlp_keys.encoder, 1)
        action = np.asarray(act(actor_params, obs))
        o, reward, terminated, truncated, _ = env.step(action.reshape(env.action_space.shape))
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
