"""Recurrent-PPO helpers (reference ``sheeprl/algos/ppo_recurrent/utils.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs
from sheeprl_tpu.algos.ppo_recurrent.agent import greedy_actions
from sheeprl_tpu.envs.vector import make_eval_env

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}


def test(agent, params, fabric, cfg, log_dir: str) -> None:
    """Greedy single-env episode carrying the LSTM state
    (reference utils.py:14-63)."""
    env = make_eval_env(cfg, log_dir)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys
    act_dim = int(sum(agent.actions_dim))

    @jax.jit
    def act(params, obs, prev_actions, is_first, hc):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        seq_obs = {k: v[None] for k, v in norm.items()}
        pre_dist, _, hc = agent.apply(
            {"params": params}, seq_obs, prev_actions[None], is_first[None], hc
        )
        return greedy_actions([p[0] for p in pre_dist], agent.is_continuous), hc

    done = False
    cumulative_rew = 0.0
    o = env.reset(seed=cfg.seed)[0]
    hc = agent.initial_hc(1)
    prev_actions = jnp.zeros((1, act_dim), jnp.float32)
    is_first = jnp.ones((1, 1), jnp.float32)
    while not done:
        obs = prepare_obs(o, cnn_keys, 1)
        real_actions, hc = act(params, obs, prev_actions, is_first, hc)
        real = np.asarray(real_actions)
        if agent.is_continuous:
            prev_actions = jnp.asarray(real, jnp.float32).reshape(1, -1)
        else:
            onehots = [
                jax.nn.one_hot(jnp.asarray(real[..., i]), d)
                for i, d in enumerate(agent.actions_dim)
            ]
            prev_actions = jnp.concatenate(onehots, -1).reshape(1, -1)
        is_first = jnp.zeros((1, 1), jnp.float32)
        o, reward, terminated, truncated, _ = env.step(
            real.reshape(env.action_space.shape)
        )
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
