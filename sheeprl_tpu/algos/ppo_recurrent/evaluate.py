"""Recurrent-PPO evaluation (reference
``sheeprl/algos/ppo_recurrent/evaluate.py``), collapsed onto the shared eval
service. The only stateful non-dreamer family: the policy state carries the
LSTM hidden pair plus the previous (one-hot) actions and the is-first flag,
all with the episode batch on axis 0 so the service's generic
finished-row reset applies."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import greedy_actions
from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
from sheeprl_tpu.algos.ppo_recurrent.utils import normalize_obs, prepare_obs
from sheeprl_tpu.evals.builders import actions_dim_of
from sheeprl_tpu.evals.service import EvalPolicy, register_eval_builder, run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_eval_builder(algorithms=["ppo_recurrent"])
def ppo_recurrent_eval_policy(fabric, cfg, state, observation_space, action_space) -> EvalPolicy:
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    actions_dim, is_continuous = actions_dim_of(action_space)
    agent = build_agent(
        cfg, actions_dim, is_continuous, list(cfg.cnn_keys.encoder), list(cfg.mlp_keys.encoder)
    )
    params = params_on_device(state["params"])
    cnn_keys = list(cfg.cnn_keys.encoder)
    obs_keys = list(cfg.mlp_keys.encoder) + cnn_keys
    act_dim = int(sum(agent.actions_dim))

    @jax.jit
    def _act(p, obs, prev_actions, is_first, hc):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        seq_obs = {k: v[None] for k, v in norm.items()}  # [T=1, B, ...]
        pre_dist, _, hc = agent.apply(
            {"params": p}, seq_obs, prev_actions[None], is_first[None], hc
        )
        return greedy_actions([pd[0] for pd in pre_dist], agent.is_continuous), hc

    def init_state(n: int):
        return {
            "hc": agent.initial_hc(n),
            "prev_actions": jnp.zeros((n, act_dim), jnp.float32),
            "is_first": jnp.ones((n, 1), jnp.float32),
        }

    def act(obs, policy_state, key):
        n = int(np.asarray(next(iter(obs.values()))).shape[0])
        prepared = prepare_obs(obs, cnn_keys, n)
        real_actions, hc = _act(
            params,
            prepared,
            jnp.asarray(policy_state["prev_actions"]),
            jnp.asarray(policy_state["is_first"]),
            jax.tree.map(jnp.asarray, policy_state["hc"]),
        )
        real = np.asarray(real_actions)
        if agent.is_continuous:
            prev_actions = jnp.asarray(real, jnp.float32).reshape(n, -1)
        else:
            onehots = [
                jax.nn.one_hot(jnp.asarray(real[..., i]), d)
                for i, d in enumerate(agent.actions_dim)
            ]
            prev_actions = jnp.concatenate(onehots, -1).reshape(n, -1)
        new_state = {
            "hc": hc,
            "prev_actions": prev_actions,
            "is_first": jnp.zeros((n, 1), jnp.float32),
        }
        return real, new_state

    return EvalPolicy(act=act, init_state=init_state)


@register_evaluation(algorithms=["ppo_recurrent"])
def evaluate_ppo_recurrent(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
