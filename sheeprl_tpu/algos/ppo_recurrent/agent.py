"""Recurrent PPO agent — LSTM policy over observation/action sequences.

Behavioral contract from the reference ``sheeprl/algos/ppo_recurrent/agent.py``
(RecurrentModel :15-74, RecurrentPPOAgent :76-290): a MultiEncoder feature
extractor, an optional pre-RNN MLP, an LSTM over ``features ‖ prev_actions``,
an optional post-RNN MLP, then the standard PPO actor heads + critic on the
recurrent output.

TPU-native design: the time loop is an ``nn.scan`` over a reset-aware LSTM
cell — per-step ``is_first`` flags zero the carried ``(c, h)`` inside the
scanned cell (the reference instead splits episodes, pads, and masks;
resetting inside a contiguous scan is the branchless equivalent when
``reset_recurrent_state_on_done`` is on, and avoids ragged/padded batches
entirely). All shapes are ``[T, B, ...]``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import (  # noqa: F401
    evaluate_actions,
    greedy_actions,
    sample_actions,
)
from sheeprl_tpu.models import MLP, NatureCNN


class _ResetLSTMCell(nn.Module):
    """LSTM cell whose carry is zeroed where ``is_first`` is set."""

    hidden_size: int

    @nn.compact
    def __call__(self, carry, inp):
        c, h = carry
        x, first = inp
        c = (1.0 - first) * c
        h = (1.0 - first) * h
        (c, h), y = nn.OptimizedLSTMCell(self.hidden_size)((c, h), x)
        return (c, h), y


class RecurrentPPOAgent(nn.Module):
    """Encoder → [pre-RNN MLP] → reset-aware LSTM scan → [post-RNN MLP] →
    actor heads + critic. Sequence-first shapes ``[T, B, ...]``."""

    actions_dim: Tuple[int, ...]
    is_continuous: bool
    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    screen_size: int
    cnn_features_dim: int = 512
    mlp_features_dim: int = 64
    encoder_dense_units: int = 64
    encoder_mlp_layers: int = 2
    encoder_dense_act: str = "relu"
    encoder_layer_norm: bool = True
    rnn_hidden_size: int = 64
    pre_rnn_apply: bool = False
    pre_rnn_dense_units: int = 64
    pre_rnn_act: str = "relu"
    pre_rnn_layer_norm: bool = True
    post_rnn_apply: bool = False
    post_rnn_dense_units: int = 64
    post_rnn_act: str = "relu"
    post_rnn_layer_norm: bool = True
    actor_dense_units: int = 128
    actor_mlp_layers: int = 1
    actor_dense_act: str = "relu"
    actor_layer_norm: bool = True
    critic_dense_units: int = 128
    critic_mlp_layers: int = 1
    critic_dense_act: str = "relu"
    critic_layer_norm: bool = True

    def setup(self) -> None:
        if self.cnn_keys:
            self.cnn_encoder = NatureCNN(
                features_dim=self.cnn_features_dim, screen_size=self.screen_size
            )
        if self.mlp_keys:
            self.mlp_encoder = MLP(
                hidden_sizes=(self.encoder_dense_units,) * self.encoder_mlp_layers,
                output_dim=self.mlp_features_dim,
                activation=self.encoder_dense_act,
                layer_norm=self.encoder_layer_norm,
            )
        if self.pre_rnn_apply:
            self.pre_rnn = MLP(
                hidden_sizes=(self.pre_rnn_dense_units,),
                activation=self.pre_rnn_act,
                layer_norm=self.pre_rnn_layer_norm,
            )
        if self.post_rnn_apply:
            self.post_rnn = MLP(
                hidden_sizes=(self.post_rnn_dense_units,),
                activation=self.post_rnn_act,
                layer_norm=self.post_rnn_layer_norm,
            )
        self.rnn = nn.scan(
            _ResetLSTMCell,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )(self.rnn_hidden_size)
        self.actor_backbone = MLP(
            hidden_sizes=(self.actor_dense_units,) * self.actor_mlp_layers,
            activation=self.actor_dense_act,
            layer_norm=self.actor_layer_norm,
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(int(sum(self.actions_dim)) * 2)]
        else:
            self.actor_heads = [nn.Dense(int(d)) for d in self.actions_dim]
        self.critic = MLP(
            hidden_sizes=(self.critic_dense_units,) * self.critic_mlp_layers,
            output_dim=1,
            activation=self.critic_dense_act,
            layer_norm=self.critic_layer_norm,
        )

    def features(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        feats = []
        if self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-3)
            feats.append(self.cnn_encoder(x))
        if self.mlp_keys:
            x = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            feats.append(self.mlp_encoder(x))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def __call__(
        self,
        obs: Dict[str, jnp.ndarray],
        prev_actions: jnp.ndarray,
        is_first: jnp.ndarray,
        hc: Tuple[jnp.ndarray, jnp.ndarray],
    ) -> Tuple[List[jnp.ndarray], jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        """``obs[k]``: [T, B, ...]; ``prev_actions``: [T, B, A]; ``is_first``:
        [T, B, 1]; ``hc``: ((c [B, H]), (h [B, H])). Returns
        ``(pre_dist, values, (c, h))``."""
        feat = self.features(obs)
        x = jnp.concatenate([feat, prev_actions], -1)
        if self.pre_rnn_apply:
            x = self.pre_rnn(x)
        hc, outs = self.rnn(hc, (x, is_first))
        if self.post_rnn_apply:
            outs = self.post_rnn(outs)
        trunk = self.actor_backbone(outs)
        pre_dist = [head(trunk) for head in self.actor_heads]
        values = self.critic(outs)
        return pre_dist, values, hc

    def initial_hc(self, batch: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        z = jnp.zeros((batch, self.rnn_hidden_size), jnp.float32)
        return (z, z)


def build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys) -> RecurrentPPOAgent:
    rnn_cfg = cfg.algo.rnn
    return RecurrentPPOAgent(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=is_continuous,
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        screen_size=int(cfg.env.screen_size),
        cnn_features_dim=int(cfg.algo.encoder.cnn_features_dim),
        mlp_features_dim=int(cfg.algo.encoder.mlp_features_dim),
        encoder_dense_units=int(cfg.algo.encoder.dense_units),
        encoder_mlp_layers=int(cfg.algo.encoder.mlp_layers),
        encoder_dense_act=cfg.algo.encoder.dense_act,
        encoder_layer_norm=bool(cfg.algo.encoder.layer_norm),
        rnn_hidden_size=int(rnn_cfg.lstm.hidden_size),
        pre_rnn_apply=bool(rnn_cfg.pre_rnn_mlp.apply),
        pre_rnn_dense_units=int(rnn_cfg.pre_rnn_mlp.dense_units),
        pre_rnn_act=rnn_cfg.pre_rnn_mlp.activation,
        pre_rnn_layer_norm=bool(rnn_cfg.pre_rnn_mlp.layer_norm),
        post_rnn_apply=bool(rnn_cfg.post_rnn_mlp.apply),
        post_rnn_dense_units=int(rnn_cfg.post_rnn_mlp.dense_units),
        post_rnn_act=rnn_cfg.post_rnn_mlp.activation,
        post_rnn_layer_norm=bool(rnn_cfg.post_rnn_mlp.layer_norm),
        actor_dense_units=int(cfg.algo.actor.dense_units),
        actor_mlp_layers=int(cfg.algo.actor.mlp_layers),
        actor_dense_act=cfg.algo.actor.dense_act,
        actor_layer_norm=bool(cfg.algo.actor.layer_norm),
        critic_dense_units=int(cfg.algo.critic.dense_units),
        critic_mlp_layers=int(cfg.algo.critic.mlp_layers),
        critic_dense_act=cfg.algo.critic.dense_act,
        critic_layer_norm=bool(cfg.algo.critic.layer_norm),
    )


def init_agent_params(agent: RecurrentPPOAgent, observation_space, cnn_keys, mlp_keys, key):
    dummy_obs = {}
    for k in list(cnn_keys) + list(mlp_keys):
        shape = observation_space[k].shape
        if k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, 1, int(np.prod(shape[:-2])), *shape[-2:]), jnp.float32)
        else:
            dummy_obs[k] = jnp.zeros((1, 1, int(np.prod(shape))), jnp.float32)
    act_dim = int(sum(agent.actions_dim))
    return agent.init(
        key,
        dummy_obs,
        jnp.zeros((1, 1, act_dim), jnp.float32),
        jnp.zeros((1, 1, 1), jnp.float32),
        agent.initial_hc(1),
    )["params"]
