"""Recurrent PPO — LSTM policy trained on replayed sequences.

Behavioral contract from the reference
``sheeprl/algos/ppo_recurrent/ppo_recurrent.py`` (train :33-107, main
:110-499): on-policy rollouts carrying LSTM state (reset on done when
``reset_recurrent_state_on_done``), GAE, then epochs × minibatches of
*sequences* with the stored initial hidden state per sequence and losses
over every step.

TPU-native design: ``rollout_steps`` must be a multiple of
``per_rank_sequence_length`` (also asserted by the reference :226-228), so
the rollout splits into fixed-shape ``[L, N_seq, ...]`` chunks — no episode
splitting, padding, or masks: the training scan zeroes the carried state at
the stored per-step ``is_first`` flags, which reproduces the reference's
split-at-done semantics branchlessly. The whole update (epochs × random
sequence minibatches) is one ``shard_map``-ped jit with ``pmean`` grads.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs
from sheeprl_tpu.algos.ppo_recurrent.agent import (
    RecurrentPPOAgent,
    build_agent,
    evaluate_actions,
    init_agent_params,
    sample_actions,
)
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    count_h2d,
    learn_probes,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of, set_lr
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, gae, normalize_tensor, polynomial_decay, save_configs
from sheeprl_tpu.utils.jax_compat import shard_map


def build_update_fn(
    agent: RecurrentPPOAgent,
    tx: optax.GradientTransformation,
    cfg,
    fabric,
    n_seq_local: int,
):
    """One SPMD program for the full recurrent-PPO update.

    ``seq_data`` leaves are ``[L, N_seq_local(*world), ...]``; ``init_hc`` is
    ``{"c","h"}: [N_seq, H]``; minibatches index the sequence axis.
    """
    epochs = int(cfg.algo.update_epochs)
    num_batches = int(cfg.get("per_rank_num_batches", 1) or 1)
    bs = max(n_seq_local // num_batches, 1)
    n_mb = n_seq_local // bs
    if n_seq_local % bs != 0:
        warnings.warn(
            f"per_rank_num_batches ({num_batches}) does not evenly divide the per-device "
            f"sequence count ({n_seq_local}); each epoch drops the tail of its shuffle"
        )
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    obs_keys = tuple(cfg.mlp_keys.encoder) + cnn_keys
    reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    norm_adv = bool(cfg.algo.normalize_advantages)
    axis = fabric.data_axis
    # learning-health probes (obs/learn): build-time gate, zero ops when off
    learn_on = probes_enabled(cfg)
    learn_clips = {"agent": clip_norm_of(tx)}

    def loss_fn(params, batch, hc, clip_coef, ent_coef):
        obs = normalize_obs(batch, cnn_keys, obs_keys)
        pre_dist, new_values, _ = agent.apply(
            {"params": params}, obs, batch["prev_actions"], batch["is_first"], hc
        )
        adv = batch["advantages"]
        if norm_adv:
            adv = normalize_tensor(adv)
        new_logprobs, entropy = evaluate_actions(
            pre_dist, batch["actions"], agent.actions_dim, agent.is_continuous
        )
        pg_loss = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
        v_loss = value_loss(
            new_values, batch["values"], batch["returns"], clip_coef, clip_vloss, reduction
        )
        ent_loss = entropy_loss(entropy, reduction)
        loss = pg_loss + vf_coef * v_loss + ent_coef * ent_loss
        return loss, jnp.stack([pg_loss, v_loss, ent_loss])

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_update(params, opt_state, seq_data, init_hc, key, clip_coef, ent_coef):
        rank = jax.lax.axis_index(axis)
        ep_keys = jax.random.split(jax.random.fold_in(key, rank), epochs)

        def epoch_step(carry, ep_key):
            params, opt_state = carry
            perm = jax.random.permutation(ep_key, n_seq_local)
            mb_idx = perm[: n_mb * bs].reshape(n_mb, bs)

            def mb_step(carry, idx):
                params, opt_state = carry
                batch = jax.tree_util.tree_map(lambda x: x[:, idx], seq_data)
                hc = (init_hc["c"][idx], init_hc["h"][idx])
                (_, metrics), grads = grad_fn(params, batch, hc, clip_coef, ent_coef)
                grads = pmean(grads, axis)
                updates, opt_state = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                if learn_on:
                    probes = learn_probes(
                        {"agent": grads},
                        params={"agent": params},
                        updates={"agent": updates},
                        losses=metrics,
                        clip_norms=learn_clips,
                    )
                    return (new_params, opt_state), (metrics, probes)
                return (new_params, opt_state), metrics

            carry, metrics = jax.lax.scan(mb_step, (params, opt_state), mb_idx)
            return carry, metrics

        (params, opt_state), ys = jax.lax.scan(epoch_step, (params, opt_state), ep_keys)
        metrics, probes = ys if learn_on else (ys, None)
        metrics = pmean(jnp.mean(metrics, axis=(0, 1)), axis)
        if learn_on:
            return params, opt_state, metrics, probes
        return params, opt_state, metrics

    shmapped = shard_map(
        local_update,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(None, axis), P(axis), P(), P(), P()),
        out_specs=(P(), P(), P()) + ((P(),) if learn_on else ()),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError(
            "MineDojo is not currently supported by PPO Recurrent agent, since it does not "
            "take into consideration the action masks provided by the environment."
        )

    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    # rollout must split evenly into sequences (reference :226-228)
    seq_len = int(cfg.get("per_rank_sequence_length") or cfg.algo.rollout_steps)
    if cfg.algo.rollout_steps % seq_len != 0:
        raise ValueError(
            f"The rollout steps ({cfg.algo.rollout_steps}) must be a multiple of the "
            f"sequence length ({seq_len})"
        )

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    envs = make_vector_env(cfg, fabric, log_dir)
    observation_space = envs.single_observation_space

    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.cnn_keys.encoder) + len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = mlp_keys + cnn_keys

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (
            envs.single_action_space.nvec.tolist()
            if is_multidiscrete
            else [envs.single_action_space.n]
        )
    )
    act_dim = int(sum(actions_dim))
    reset_on_done = bool(cfg.algo.get("reset_recurrent_state_on_done", True))

    agent = build_agent(cfg, actions_dim, is_continuous, cnn_keys, mlp_keys)
    root_key, init_key = jax.random.split(root_key)
    params = init_agent_params(agent, observation_space, cnn_keys, mlp_keys, init_key)

    tx = instantiate(cfg.algo.optimizer, max_grad_norm=cfg.algo.max_grad_norm or None)
    opt_state = tx.init(params)

    if cfg.checkpoint.resume_from:
        template = {
            "params": params,
            "opt_state": opt_state,
            "update": 0,
            "num_batches": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        params = state["params"]
        opt_state = state["opt_state"]
        cfg.per_rank_num_batches = int(np.asarray(state["num_batches"]))
    params = jax.device_put(params, fabric.replicated)
    opt_state = jax.device_put(opt_state, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rollout_steps = int(cfg.algo.rollout_steps)
    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=obs_keys,
        size=int(cfg.buffer.size),
        min_size=rollout_steps,
        sampled=False,
    )

    # ------------------------------------------------------------------
    # jitted programs
    # ------------------------------------------------------------------

    def _act_fn(params, carry, key):
        # the key advances INSIDE the jitted burst (one dispatch per
        # env.act_burst env steps); the policy body is the old per-step
        # policy_step_fn verbatim. The LSTM state rides in the carry pytree:
        # the host callback gets both the pre-step hidden state (recorded as
        # the sequence-chunk initials) and the post-step one, applies the
        # done mask exactly where the per-step loop did, and returns the
        # masked state for the next in-scan act.
        key, sub = jax.random.split(key)
        obs = {k: carry[k] for k in obs_keys}
        hc_in = (carry["hc_c"], carry["hc_h"])
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        seq_obs = {k: v[None] for k, v in norm.items()}
        pre_dist, values, hc_out = agent.apply(
            {"params": params}, seq_obs, carry["prev_actions"][None], carry["is_first"][None], hc_in
        )
        pre_dist = [p[0] for p in pre_dist]
        actions, real_actions, logprob = sample_actions(pre_dist, is_continuous, sub)
        cb_args = (
            actions,
            real_actions,
            logprob,
            values[0],
            hc_in[0],
            hc_in[1],
            hc_out[0],
            hc_out[1],
            carry["prev_actions"],
            carry["is_first"],
        )
        return cb_args, key

    @jax.jit
    def value_fn(params, obs, prev_actions, is_first, hc):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        seq_obs = {k: v[None] for k, v in norm.items()}
        _, values, _ = agent.apply(
            {"params": params}, seq_obs, prev_actions[None], is_first[None], hc
        )
        return values[0]

    gamma, gae_lambda = float(cfg.algo.gamma), float(cfg.algo.gae_lambda)

    @jax.jit
    def gae_fn(rewards, values, dones, next_values):
        return gae(rewards, values, dones, next_values, gamma, gae_lambda)

    n_seq_local = (rollout_steps // seq_len) * int(cfg.env.num_envs)
    update_fn = build_update_fn(agent, tx, cfg, fabric, n_seq_local)
    seq_sharding = fabric.sharding(None, fabric.data_axis)
    hc_sharding = fabric.data_sharding

    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = (
        int(np.asarray(state["update"])) * cfg.env.num_envs * rollout_steps
        if state is not None
        else 0
    )
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs * rollout_steps)
    num_updates = int(cfg.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    obs = envs.reset(seed=cfg.seed)[0]
    next_obs = prepare_obs(obs, cnn_keys, n_envs)
    hc0 = agent.initial_hc(n_envs)
    carry = {
        **next_obs,
        "prev_actions": np.zeros((n_envs, act_dim), np.float32),
        "is_first": np.ones((n_envs, 1), np.float32),
        "hc_c": np.asarray(hc0[0], np.float32),
        "hc_h": np.asarray(hc0[1], np.float32),
    }
    root_key, play_key = jax.random.split(root_key)

    # Burst acting (envs/rollout, howto/rollout_engine.md): the acting loop
    # body below is the old per-step block moved into a host callback; the
    # BurstActor scans it env.act_burst times per device dispatch. The host
    # keeps the recurrent bookkeeping it has always owned — hidden-state
    # recording, done masking, prev_action/is_first resets — and threads
    # everything back through the burst carry.
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    state_box = {"carry": carry, "policy_step": policy_step, "t": 0, "cx": None, "hx": None}
    #: (ring row, truncated env ids, prepared final obs, actions, unmasked
    #: hc) per truncation — the V(s') bootstrap is patched into the stored
    #: rewards after the burst returns (the jitted burst cannot re-enter the
    #: device)
    trunc_events = []

    def _host_env_step(
        actions, real_actions, logprob, values, hc_in_c, hc_in_h, hc_out_c, hc_out_h, prev_actions, is_first
    ):
        t = state_box["t"]
        state_box["t"] = t + 1
        state_box["policy_step"] += n_envs
        state_box["cx"][t] = np.asarray(hc_in_c)
        state_box["hx"][t] = np.asarray(hc_in_h)
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            real_actions = np.asarray(real_actions)
            obs, rewards, terminated, truncated, info = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )

        actions = np.asarray(actions)
        hc_out = (np.asarray(hc_out_c), np.asarray(hc_out_h))
        truncated_envs = np.nonzero(truncated)[0]
        if len(truncated_envs) > 0:
            # bootstrap V(s') into the reward on truncation, deferred to the
            # end of the burst (the pre-mask hidden state and this step's
            # actions are what the per-step path fed value_fn inline)
            final_obs = info["final_obs"]
            t_obs = {
                k: np.stack([np.asarray(final_obs[te][k]) for te in truncated_envs])
                for k in obs_keys
            }
            t_obs = prepare_obs(t_obs, cnn_keys, len(truncated_envs))
            t_hc = (hc_out[0][truncated_envs].copy(), hc_out[1][truncated_envs].copy())
            t_actions = actions[truncated_envs].reshape(len(truncated_envs), -1).copy()
            trunc_events.append((int(rb._pos), truncated_envs, t_obs, t_actions, t_hc))

        dones = np.logical_or(terminated, truncated).astype(np.float32)
        rewards = np.asarray(rewards, dtype=np.float32)

        prev_actions = np.asarray(prev_actions)
        is_first = np.asarray(is_first)
        step_data = {
            **{k: np.asarray(state_box["carry"][k])[None] for k in obs_keys},
            "dones": dones.reshape(1, n_envs, 1),
            "values": np.asarray(values).reshape(1, n_envs, 1),
            "actions": actions.reshape(1, n_envs, -1),
            "prev_actions": prev_actions[None].copy(),
            "is_first": is_first[None].copy(),
            "logprobs": np.asarray(logprob).reshape(1, n_envs, 1),
            "rewards": rewards.reshape(1, n_envs, 1),
        }
        rb.add(step_data)

        next_prev_actions = np.array(actions, np.float32).reshape(n_envs, -1)
        if reset_on_done:
            next_is_first = dones.reshape(n_envs, 1).copy()
            next_prev_actions[dones.reshape(-1) > 0] = 0.0
            if np.any(dones):
                mask = (1.0 - dones.reshape(n_envs, 1)).astype(np.float32)
                hc_out = (hc_out[0] * mask, hc_out[1] * mask)
        else:
            next_is_first = np.zeros((n_envs, 1), np.float32)

        if cfg.metric.log_level > 0 and "final_info" in info:
            fi = info["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        new_carry = {
            **prepare_obs(obs, cnn_keys, n_envs),
            "prev_actions": next_prev_actions,
            "is_first": next_is_first,
            "hc_c": hc_out[0],
            "hc_h": hc_out[1],
        }
        state_box["carry"] = new_carry
        return new_carry

    burst_actor = BurstActor(_act_fn, _host_env_step, carry)

    for update in range(start_step, num_updates + 1):
        if cfg.algo.anneal_lr:
            lr = polynomial_decay(
                update - 1,
                initial=cfg.algo.optimizer.lr,
                final=0.0,
                max_decay_steps=num_updates,
                power=1.0,
            )
            opt_state = set_lr(opt_state, lr)
        else:
            lr = cfg.algo.optimizer.lr

        state_box["hx"] = np.empty((rollout_steps, n_envs, agent.rnn_hidden_size), np.float32)
        state_box["cx"] = np.empty((rollout_steps, n_envs, agent.rnn_hidden_size), np.float32)
        state_box["t"] = 0

        remaining = rollout_steps
        while remaining > 0:
            n_act = min(act_burst, remaining)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, play_key = burst_actor.rollout(
                    params, state_box["carry"], play_key, n_act
                )
            remaining -= n_act
        policy_step = state_box["policy_step"]
        hx_steps, cx_steps = state_box["hx"], state_box["cx"]

        # patch the deferred V(s') truncation bootstraps into the stored
        # rewards (params were frozen for the whole rollout, so the values
        # match what the per-step path computed inline)
        for row, tr_envs, t_obs, t_actions, t_hc in trunc_events:
            vals = np.asarray(
                value_fn(
                    params,
                    t_obs,
                    jnp.asarray(t_actions),
                    jnp.zeros((len(tr_envs), 1), jnp.float32),
                    (jnp.asarray(t_hc[0]), jnp.asarray(t_hc[1])),
                )
            ).reshape(-1)
            rewards_buf = rb["rewards"]
            rewards_buf[row, tr_envs, 0] = rewards_buf[row, tr_envs, 0] + vals
        trunc_events.clear()

        carry = state_box["carry"]
        next_obs = {k: carry[k] for k in obs_keys}
        prev_actions = carry["prev_actions"]
        is_first = carry["is_first"]
        hc = (jnp.asarray(carry["hc_c"]), jnp.asarray(carry["hc_h"]))

        # GAE over the rollout
        next_values = value_fn(
            params, next_obs, jnp.asarray(prev_actions), jnp.asarray(is_first), hc
        )
        returns, advantages = gae_fn(
            np.asarray(rb["rewards"]), np.asarray(rb["values"]), np.asarray(rb["dones"]), next_values
        )

        # Chunk the rollout into [L, N_seq, ...] sequences: [T, E] → env-major
        # [(T/L)*E sequences] so device shards own whole envs.
        n_chunks = rollout_steps // seq_len

        def to_seq(x):
            x = np.asarray(x)[:rollout_steps]
            # [T, E, ...] → [n_chunks, L, E, ...] → [L, E, n_chunks, ...] → [L, E*n_chunks, ...]
            x = x.reshape((n_chunks, seq_len) + x.shape[1:])
            x = np.moveaxis(x, 0, 2)
            return x.reshape((seq_len, n_envs * n_chunks) + x.shape[3:])

        seq_data = {
            **{k: to_seq(rb[k]) for k in obs_keys},
            "actions": to_seq(rb["actions"]),
            "prev_actions": to_seq(rb["prev_actions"]),
            "is_first": to_seq(rb["is_first"]),
            "logprobs": to_seq(rb["logprobs"]),
            "values": to_seq(rb["values"]),
            "returns": to_seq(np.asarray(returns)),
            "advantages": to_seq(np.asarray(advantages)),
        }
        # initial hidden state of every chunk: [E, n_chunks, H] → [E*n_chunks, H]
        def to_hc(x):
            x = x[::seq_len]  # [n_chunks, E, H]
            return np.moveaxis(x, 0, 1).reshape(n_envs * n_chunks, -1)

        init_hc = {"c": to_hc(cx_steps), "h": to_hc(hx_steps)}

        count_h2d(seq_data)
        count_h2d(init_hc)
        with span("Time/stage_h2d_time", phase="stage_h2d"):
            seq_data = jax.device_put(seq_data, seq_sharding)
            init_hc = jax.device_put(init_hc, hc_sharding)

        with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
            root_key, update_key = jax.random.split(root_key)
            outs = update_fn(
                params,
                opt_state,
                seq_data,
                init_hc,
                update_key,
                jnp.float32(cfg.algo.clip_coef),
                jnp.float32(cfg.algo.ent_coef),
            )
            params, opt_state, losses = outs[0], outs[1], outs[2]
            observe_probes(outs[3] if len(outs) > 3 else None, step=policy_step)
            losses = fetch_losses_if_observed(losses, aggregator)
        train_step += world_size

        if aggregator and not aggregator.disabled:
            aggregator.update("Loss/policy_loss", losses[0])
            aggregator.update("Loss/value_loss", losses[1])
            aggregator.update("Loss/entropy_loss", losses[2])

        if cfg.metric.log_level > 0 and logger is not None:
            logger.log_metrics({"Info/learning_rate": lr}, policy_step)
            logger.log_metrics({"Info/clip_coef": cfg.algo.clip_coef}, policy_step)
            logger.log_metrics({"Info/ent_coef": cfg.algo.ent_coef}, policy_step)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or update == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if cfg.algo.anneal_clip_coef:
            cfg.algo.clip_coef = polynomial_decay(
                update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            cfg.algo.ent_coef = polynomial_decay(
                update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )

        if should_checkpoint(cfg, policy_step, last_checkpoint, update, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "params": jax.device_get(params),
                "opt_state": jax.device_get(opt_state),
                "update": update * world_size,
                "num_batches": int(cfg.get("per_rank_num_batches", 1) or 1),
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        from sheeprl_tpu.algos.ppo_recurrent.utils import test

        test(agent, jax.device_get(params), fabric, cfg, log_dir)
