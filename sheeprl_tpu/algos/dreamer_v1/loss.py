"""DreamerV1 world-model loss (reference ``sheeprl/algos/dreamer_v1/loss.py``:
reconstruction_loss :30-94).

Eq. 10 of the DV1 paper: Gaussian NLL of observations/rewards (+ optional
Bernoulli continue NLL) plus ``kl_regularizer · max(free_nats, KL(post ‖
prior))`` where the free-nats clamp applies to the *mean* KL of the Gaussian
latents.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.distributions import Independent, Normal, kl_divergence

sg = jax.lax.stop_gradient


def reconstruction_loss(
    qo: Dict[str, Any],
    observations: Dict[str, jnp.ndarray],
    qr: Any,
    rewards: jnp.ndarray,
    posteriors_dist: Any,
    priors_dist: Any,
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    qc: Optional[Any] = None,
    continue_targets: Optional[jnp.ndarray] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """``posteriors_dist``/``priors_dist`` are Independent Normals over
    ``[T, B, S]``. Returns ``(scalar_loss, metrics)``."""
    observation_loss = -sum(jnp.mean(qo[k].log_prob(observations[k])) for k in qo)
    reward_loss = -jnp.mean(qr.log_prob(rewards))
    kl = jnp.mean(kl_divergence(posteriors_dist, priors_dist))
    state_loss = jnp.maximum(jnp.asarray(kl_free_nats, kl.dtype), kl)
    continue_loss = jnp.zeros(())
    if qc is not None and continue_targets is not None:
        continue_loss = continue_scale_factor * -jnp.mean(qc.log_prob(continue_targets))
    total = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    metrics = {
        "Loss/world_model_loss": total,
        "Loss/observation_loss": observation_loss,
        "Loss/reward_loss": reward_loss,
        "Loss/state_loss": state_loss,
        "Loss/continue_loss": continue_loss,
        "State/kl": kl,
        "State/post_entropy": jnp.mean(posteriors_dist.entropy()),
        "State/prior_entropy": jnp.mean(priors_dist.entropy()),
    }
    return total, metrics


def gaussian_independent(mean: jnp.ndarray, std, ndims: int = 1) -> Independent:
    """Independent unit-or-given-σ Normal helper for obs/reward/value heads."""
    std_arr = jnp.broadcast_to(jnp.asarray(std, mean.dtype), mean.shape)
    return Independent(Normal(mean, std_arr), ndims)
