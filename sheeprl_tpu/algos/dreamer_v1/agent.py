"""DreamerV1 agent — flax modules, functional player, Xavier init.

Behavioral contract from the reference ``sheeprl/algos/dreamer_v1/agent.py``
(RecurrentModel :29-59, RSSM :62-191, WorldModel :193-218, PlayerDV1 :221-340,
build_agent :343-540). V1 reuses the V2 encoder/decoder geometry (the
reference imports them directly, agent.py:15-18) but differs in the core:

- **Gaussian latent**: the representation/transition heads emit
  ``2·stochastic_size`` (mean ‖ raw-std); the state is
  ``Normal(mean, softplus(std) + min_std).rsample()``
  (reference dreamer_v1/utils.py compute_stochastic_state :66-93);
- plain GRU cell after a ``Linear(→recurrent_state_size) + act`` pre-layer
  (reference :41-43) — no LayerNorm anywhere;
- ``dynamic`` has **no** is_first reset (reference :95-133);
- ReLU convs / ELU denses, Xavier-normal init.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu import kernels
from sheeprl_tpu.algos.dreamer_v2.agent import (
    CNNDecoder,
    CNNEncoder,
    MLPDecoder,
    MLPEncoder,
    MLPHead,
    cnn_encoder_output_dim,
    xavier_normal_initialization,
)
from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    actor_entropy,
    add_exploration_noise,
    build_actor_dists,
    resolve_actor_distribution,
    sample_actor_actions,
)
from sheeprl_tpu.distributions import Independent, Normal

sg = jax.lax.stop_gradient

__all__ = [
    "Actor",
    "RecurrentModel",
    "RSSM",
    "WorldModel",
    "MLPHead",
    "actor_entropy",
    "add_exploration_noise",
    "build_actor_dists",
    "build_agent",
    "build_player_fns",
    "compute_stochastic_state",
    "resolve_actor_distribution",
    "sample_actor_actions",
]


def compute_stochastic_state(
    state_information: jnp.ndarray,
    key: Optional[jax.Array],
    min_std: float = 0.1,
    noise: Optional[jnp.ndarray] = None,
) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], jnp.ndarray]:
    """``[..., 2S]`` head output → ``((mean, std), sampled state)`` with
    ``std = softplus(raw) + min_std`` (reference dv1/utils.py:66-93). With no
    key the mean is returned (the deterministic player-init path).

    ``noise`` is pre-drawn N(0,1): train scans draw it for the whole
    sequence in one call outside the time loop (see the DV3 agent)."""
    mean, std = jnp.split(state_information, 2, axis=-1)
    std = jax.nn.softplus(std) + min_std
    if noise is not None:
        return (mean, std), mean + std * noise
    if key is None:
        return (mean, std), mean
    state = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
    return (mean, std), state


class RecurrentModel(nn.Module):
    """Linear(→recurrent size) + activation + plain GRU cell
    (reference agent.py:29-59)."""

    recurrent_state_size: int
    activation: Any = "elu"
    fused: str = "off"  # resolved kernel tier (sheeprl_tpu/kernels)

    @nn.compact
    def __call__(self, x: jnp.ndarray, h: jnp.ndarray) -> jnp.ndarray:
        from sheeprl_tpu.models import FusedGRUCell, resolve_activation

        feat = nn.Dense(self.recurrent_state_size)(x)
        feat = resolve_activation(self.activation)(feat)
        # FusedGRUCell is parameter- and bitwise-compatible with the
        # nn.GRUCell it replaced; fused="off" is the flax math verbatim
        return FusedGRUCell(self.recurrent_state_size, name="gru", fused=self.fused)(h, feat)[1]


class _GaussianStochasticModel(nn.Module):
    """MLP trunk + ``2S`` head for the prior/posterior (reference
    build_agent :396-411)."""

    hidden_size: int
    stochastic_size: int
    activation: Any = "elu"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from sheeprl_tpu.models import MLP

        x = MLP(hidden_sizes=[self.hidden_size], activation=self.activation)(x)
        return nn.Dense(2 * self.stochastic_size, name="head")(x)


class RSSM(nn.Module):
    """Gaussian-latent RSSM (reference agent.py:62-191). Single-step methods;
    callers scan over time. No is_first resets."""

    recurrent_state_size: int
    stochastic_size: int
    hidden_size: int
    representation_hidden_size: Optional[int] = None
    min_std: float = 0.1
    activation: Any = "elu"
    fused: str = "off"

    def setup(self):
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            activation=self.activation,
            fused=self.fused,
        )
        self.representation_model = _GaussianStochasticModel(
            hidden_size=self.representation_hidden_size or self.hidden_size,
            stochastic_size=self.stochastic_size,
            activation=self.activation,
        )
        self.transition_model = _GaussianStochasticModel(
            hidden_size=self.hidden_size,
            stochastic_size=self.stochastic_size,
            activation=self.activation,
        )

    def _transition(
        self,
        recurrent_out: jnp.ndarray,
        key: Optional[jax.Array],
        noise: Optional[jnp.ndarray] = None,
    ) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], jnp.ndarray]:
        return compute_stochastic_state(
            self.transition_model(recurrent_out), key, self.min_std, noise=noise
        )

    def _representation(
        self,
        recurrent_state: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        key: Optional[jax.Array],
        noise: Optional[jnp.ndarray] = None,
    ) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], jnp.ndarray]:
        return compute_stochastic_state(
            self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], -1)),
            key,
            self.min_std,
            noise=noise,
        )

    def dynamic(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        key: jax.Array,
    ):
        """One posterior step (reference :95-133). Returns ``(recurrent_state,
        posterior, (post_mean, post_std), (prior_mean, prior_std))``."""
        recurrent_state, posterior, posterior_mean_std = self.dynamic_posterior(
            posterior, recurrent_state, action, embedded_obs, key
        )
        prior_mean_std = self.prior_stats(recurrent_state)
        return recurrent_state, posterior, posterior_mean_std, prior_mean_std

    def dynamic_posterior(
        self,
        posterior: jnp.ndarray,
        recurrent_state: jnp.ndarray,
        action: jnp.ndarray,
        embedded_obs: jnp.ndarray,
        key: Optional[jax.Array],
        noise: Optional[jnp.ndarray] = None,
    ):
        """Sequential core of ``dynamic``: the prior (transition) stats never
        feed back into the time loop — train scans batch :meth:`prior_stats`
        over the [T, B] output afterwards (same optimization as DV3)."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], -1), recurrent_state
        )
        if noise is None:
            # same split as dynamic() (whose k1 sampled the discarded prior)
            key = jax.random.split(key)[1]
        posterior_mean_std, posterior = self._representation(
            recurrent_state, embedded_obs, key, noise=noise
        )
        return recurrent_state, posterior, posterior_mean_std

    def prior_stats(self, recurrent_states: jnp.ndarray):
        """Prior ``(mean, std)`` — batchable over any leading shape."""
        return compute_stochastic_state(
            self.transition_model(recurrent_states), None, self.min_std
        )[0]

    def imagination(
        self, stochastic_state: jnp.ndarray, recurrent_state: jnp.ndarray,
        actions: jnp.ndarray, key: Optional[jax.Array],
        noise: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """One prior step in imagination (reference :171-191)."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([stochastic_state, actions], -1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key, noise=noise)
        return imagined_prior, recurrent_state

    def __call__(self, posterior, recurrent_state, action, embedded_obs, key):
        return self.dynamic(posterior, recurrent_state, action, embedded_obs, key)


class WorldModel(nn.Module):
    """Encoder + Gaussian RSSM + observation/reward/[continue] heads
    (reference agent.py:193-218)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_channels: Sequence[int]
    mlp_dims: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    encoder_mlp_layers: int
    decoder_mlp_layers: int
    dense_units: int
    recurrent_state_size: int
    stochastic_size: int
    hidden_size: int
    representation_hidden_size: Optional[int] = None
    reward_mlp_layers: Optional[int] = None
    reward_dense_units: Optional[int] = None
    continue_mlp_layers: Optional[int] = None
    continue_dense_units: Optional[int] = None
    use_continues: bool = False
    min_std: float = 0.1
    cnn_act: Any = "relu"
    dense_act: Any = "elu"
    fused: str = "off"

    def setup(self):
        if self.cnn_keys:
            self.cnn_encoder = CNNEncoder(
                keys=self.cnn_keys,
                channels_multiplier=self.channels_multiplier,
                layer_norm=False,
                activation=self.cnn_act,
            )
            self.cnn_decoder = CNNDecoder(
                output_channels=self.cnn_channels,
                channels_multiplier=self.channels_multiplier,
                cnn_encoder_output_dim=cnn_encoder_output_dim(
                    self.image_size, self.channels_multiplier
                ),
                layer_norm=False,
                activation=self.cnn_act,
            )
        if self.mlp_keys:
            self.mlp_encoder = MLPEncoder(
                keys=self.mlp_keys,
                mlp_layers=self.encoder_mlp_layers,
                dense_units=self.dense_units,
                layer_norm=False,
                activation=self.dense_act,
            )
            self.mlp_decoder = MLPDecoder(
                keys=self.mlp_keys,
                output_dims=self.mlp_dims,
                mlp_layers=self.decoder_mlp_layers,
                dense_units=self.dense_units,
                layer_norm=False,
                activation=self.dense_act,
            )
        self.rssm = RSSM(
            recurrent_state_size=self.recurrent_state_size,
            stochastic_size=self.stochastic_size,
            hidden_size=self.hidden_size,
            representation_hidden_size=self.representation_hidden_size,
            min_std=self.min_std,
            activation=self.dense_act,
            fused=self.fused,
        )
        self.reward_model = MLPHead(
            output_dim=1,
            mlp_layers=self.reward_mlp_layers or self.decoder_mlp_layers,
            dense_units=self.reward_dense_units or self.dense_units,
            layer_norm=False,
            activation=self.dense_act,
        )
        if self.use_continues:
            self.continue_model = MLPHead(
                output_dim=1,
                mlp_layers=self.continue_mlp_layers or self.decoder_mlp_layers,
                dense_units=self.continue_dense_units or self.dense_units,
                layer_norm=False,
                activation=self.dense_act,
            )

    # -- methods for apply(..., method=...) --------------------------------

    def encode(self, obs: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        feats = []
        if self.cnn_keys:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_keys:
            feats.append(self.mlp_encoder(obs))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, key):
        return self.rssm.dynamic(posterior, recurrent_state, action, embedded_obs, key)

    def dynamic_posterior(self, posterior, recurrent_state, action, embedded_obs, key, noise=None):
        return self.rssm.dynamic_posterior(
            posterior, recurrent_state, action, embedded_obs, key, noise
        )

    def prior_stats(self, recurrent_states):
        return self.rssm.prior_stats(recurrent_states)

    def imagination(self, prior, recurrent_state, actions, key, noise=None):
        return self.rssm.imagination(prior, recurrent_state, actions, key, noise=noise)

    def recurrent_step(self, stochastic, actions, recurrent_state):
        return self.rssm.recurrent_model(
            jnp.concatenate([stochastic, actions], -1), recurrent_state
        )

    def representation(self, recurrent_state, embedded_obs, key):
        return self.rssm._representation(recurrent_state, embedded_obs, key)

    def decode(self, latent: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        out: Dict[str, jnp.ndarray] = {}
        if self.cnn_keys:
            rec = self.cnn_decoder(latent)
            if len(self.cnn_keys) > 1:
                parts = jnp.split(rec, np.cumsum(np.asarray(self.cnn_channels))[:-1], axis=-3)
            else:
                parts = [rec]
            out.update({k: v for k, v in zip(self.cnn_keys, parts)})
        if self.mlp_keys:
            out.update(self.mlp_decoder(latent))
        return out

    def reward(self, latent: jnp.ndarray) -> jnp.ndarray:
        return self.reward_model(latent)

    def continues(self, latent: jnp.ndarray) -> jnp.ndarray:
        return self.continue_model(latent)

    def __call__(self, obs, posterior, recurrent_state, action, key):
        """Init-path: touches every submodule once."""
        embed = self.encode(obs)
        recurrent_state, posterior, post_ms, prior_ms = self.rssm.dynamic(
            posterior, recurrent_state, action, embed, key
        )
        latent = jnp.concatenate([posterior, recurrent_state], -1)
        recon = self.decode(latent)
        cont = self.continue_model(latent) if self.use_continues else None
        return recurrent_state, posterior, post_ms, prior_ms, recon, self.reward_model(latent), cont


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    observation_space,
    key: jax.Array,
) -> Tuple[WorldModel, Actor, MLPHead, Dict[str, Any]]:
    """Construct module defs + Xavier-initialized params (reference
    build_agent, agent.py:343-540)."""
    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    screen = int(cfg.env.screen_size)
    cnn_channels = [int(np.prod(observation_space[k].shape[:-2])) for k in cnn_keys]
    mlp_dims = [int(np.prod(observation_space[k].shape)) for k in mlp_keys]
    # DV1's recurrent core is flax-GRU math: a `pallas` request degrades to
    # the padded-XLA tier inside resolve_tier (family has no Pallas kernel)
    fused = kernels.resolve_tier(cfg.algo.get("fused_kernels", "off"), family="flax_gru")

    world_model = WorldModel(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_channels=cnn_channels,
        mlp_dims=mlp_dims,
        image_size=(screen, screen),
        channels_multiplier=int(wm_cfg.encoder.cnn_channels_multiplier),
        encoder_mlp_layers=int(wm_cfg.encoder.mlp_layers),
        decoder_mlp_layers=int(wm_cfg.observation_model.mlp_layers),
        dense_units=int(wm_cfg.encoder.dense_units),
        recurrent_state_size=int(wm_cfg.recurrent_model.recurrent_state_size),
        stochastic_size=int(wm_cfg.stochastic_size),
        hidden_size=int(wm_cfg.transition_model.hidden_size),
        representation_hidden_size=int(wm_cfg.representation_model.hidden_size),
        reward_mlp_layers=int(wm_cfg.reward_model.mlp_layers),
        reward_dense_units=int(wm_cfg.reward_model.dense_units),
        continue_mlp_layers=int(wm_cfg.discount_model.mlp_layers),
        continue_dense_units=int(wm_cfg.discount_model.dense_units),
        use_continues=bool(wm_cfg.use_continues),
        min_std=float(wm_cfg.min_std),
        cnn_act=cfg.algo.cnn_act,
        dense_act=cfg.algo.dense_act,
        fused=fused,
    )
    latent_size = int(wm_cfg.stochastic_size) + int(wm_cfg.recurrent_model.recurrent_state_size)
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=resolve_actor_distribution(
            cfg.distribution.get("type", "auto"), is_continuous
        ),
        dense_units=int(cfg.algo.actor.dense_units),
        mlp_layers=int(cfg.algo.actor.mlp_layers),
        layer_norm=False,
        activation=cfg.algo.actor.dense_act,
    )
    critic = MLPHead(
        output_dim=1,
        mlp_layers=int(cfg.algo.critic.mlp_layers),
        dense_units=int(cfg.algo.critic.dense_units),
        layer_norm=False,
        activation=cfg.algo.critic.dense_act,
    )

    k_wm, k_actor, k_critic, k_xw, k_xa, k_xc, k_s = jax.random.split(key, 7)
    dummy_obs = {}
    for k, ch in zip(cnn_keys, cnn_channels):
        dummy_obs[k] = jnp.zeros((1, ch, screen, screen), jnp.float32)
    for k, dim in zip(mlp_keys, mlp_dims):
        dummy_obs[k] = jnp.zeros((1, dim), jnp.float32)
    stoch = int(wm_cfg.stochastic_size)
    rec = int(wm_cfg.recurrent_model.recurrent_state_size)
    act_dim = int(np.sum(actions_dim))

    wm_params = world_model.init(
        k_wm,
        dummy_obs,
        jnp.zeros((1, stoch)),
        jnp.zeros((1, rec)),
        jnp.zeros((1, act_dim)),
        k_s,
    )["params"]
    actor_params = actor.init(k_actor, jnp.zeros((1, latent_size)))["params"]
    critic_params = critic.init(k_critic, jnp.zeros((1, latent_size)))["params"]

    wm_params = xavier_normal_initialization(wm_params, k_xw)
    actor_params = xavier_normal_initialization(actor_params, k_xa)
    critic_params = xavier_normal_initialization(critic_params, k_xc)

    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
    }
    return world_model, actor, critic, params


# ---------------------------------------------------------------------------
# functional player (reference PlayerDV1, agent.py:221-340)
# ---------------------------------------------------------------------------


def build_player_fns(
    world_model: WorldModel,
    actor: Actor,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
):
    """Pure jitted player over an explicit ``{"actions", "recurrent",
    "stochastic"}`` pytree; zero-init states (reference init_states :300-310)."""
    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)
    rec_size = int(cfg.algo.world_model.recurrent_model.recurrent_state_size)
    stoch_size = int(cfg.algo.world_model.stochastic_size)
    act_dim = int(np.sum(actions_dim))

    def init_states(wm_params, n_envs: int):
        del wm_params
        return {
            "actions": jnp.zeros((n_envs, act_dim)),
            "recurrent": jnp.zeros((n_envs, rec_size)),
            "stochastic": jnp.zeros((n_envs, stoch_size)),
        }

    def reset_states(wm_params, state, reset_mask):
        del wm_params
        return jax.tree_util.tree_map(lambda s: (1.0 - reset_mask) * s, state)

    def _step(wm_params, actor_params, state, obs, key, is_training: bool):
        embed = world_model.apply({"params": wm_params}, obs, method=WorldModel.encode)
        recurrent = world_model.apply(
            {"params": wm_params},
            state["stochastic"],
            state["actions"],
            state["recurrent"],
            method=WorldModel.recurrent_step,
        )
        k_repr, k_act = jax.random.split(key)
        _, stochastic = world_model.apply(
            {"params": wm_params}, recurrent, embed, k_repr, method=WorldModel.representation
        )
        latent = jnp.concatenate([stochastic, recurrent], -1)
        pre_dist = actor.apply({"params": actor_params}, latent)
        dists = build_actor_dists(
            pre_dist, is_continuous, distribution, init_std, min_std, unimix=0.0
        )
        actions = sample_actor_actions(dists, is_continuous, k_act, is_training)
        new_state = {
            "actions": jnp.concatenate(actions, -1),
            "recurrent": recurrent,
            "stochastic": stochastic,
        }
        return actions, new_state

    @jax.jit
    def greedy_action(wm_params, actor_params, state, obs, key):
        return _step(wm_params, actor_params, state, obs, key, is_training=False)

    @jax.jit
    def exploration_action(wm_params, actor_params, state, obs, key, expl_amount):
        k_step, k_expl = jax.random.split(key)
        actions, new_state = _step(wm_params, actor_params, state, obs, k_step, is_training=True)
        expl = add_exploration_noise(actions, expl_amount, is_continuous, k_expl)
        new_state = dict(new_state, actions=jnp.concatenate(expl, -1))
        return expl, new_state

    return {
        "init_states": init_states,
        "reset_states": jax.jit(reset_states),
        "greedy_action": greedy_action,
        "exploration_action": exploration_action,
    }
