"""DreamerV1 utilities (reference ``sheeprl/algos/dreamer_v1/utils.py``).

- :data:`AGGREGATOR_KEYS` — the metric allow-list (reference :17-27).
- :func:`compute_lambda_values` — the V1 recursion (reference :28-63):
  ``H−1`` targets, the pre-terminal step bootstrapping with the *full* last
  value while earlier steps mix ``(1−λ)·v_{t+1}``.
- obs normalization: V1 pixels are scaled to ``[-0.5, 0.5]`` like V2.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.utils import normalize_obs_jnp  # noqa: F401
from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Params/exploration_amount",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}


def compute_lambda_values(
    rewards: jnp.ndarray,
    values: jnp.ndarray,
    continues: jnp.ndarray,
    last_values: jnp.ndarray,
    lmbda: float = 0.95,
) -> jnp.ndarray:
    """V1 λ-targets over ``[H, ...]`` inputs → ``[H−1, ...]`` outputs
    (reference dv1/utils.py:28-63): for t < H−2 the one-step bootstrap is
    ``(1−λ)·v_{t+1}``; at t = H−2 it is the full ``last_values``; the running
    λ-accumulator starts at 0."""
    horizon = rewards.shape[0]
    rewards = jnp.asarray(rewards)
    values = jnp.asarray(values)
    continues = jnp.asarray(continues)
    next_values = values[1:] * (1 - lmbda)
    next_values = next_values.at[-1].set(jnp.asarray(last_values))
    inputs = rewards[: horizon - 1] + next_values * continues[: horizon - 1]

    def step(last_lv, inp):
        delta, cont = inp
        lv = delta + lmbda * cont * last_lv
        return lv, lv

    _, lv = jax.lax.scan(
        step,
        jnp.zeros_like(values[0]),
        (inputs, continues[: horizon - 1]),
        reverse=True,
    )
    return lv
