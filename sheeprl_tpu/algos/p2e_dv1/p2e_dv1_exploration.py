"""Plan2Explore-DV1, exploration phase.

Behavioral contract from the reference
``sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py`` (train :38-390, main
:393-800): DV1 world-model learning, plus

- **ensemble learning** (:200-222): members regress the next *observation
  embedding* with a unit-Gaussian NLL;
- **exploration behaviour** (:224-330): DV1-style H-step imagination with
  the exploration actor; intrinsic reward = ensemble disagreement ×
  multiplier; pure dynamics-backprop actor loss
  ``-mean(discount · λ-values)``; Gaussian exploration critic (V1 has no
  target critics);
- **task behaviour** (:332-390): the plain DV1 actor-critic update.

TPU-native: one fused ``shard_map``-ped jit per gradient step; the shared
behaviour closure is instantiated twice (intrinsic / extrinsic reward).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v1.agent import (
    Actor,
    WorldModel,
    build_actor_dists,
    resolve_actor_distribution,
    sample_actor_actions,
)
from sheeprl_tpu.algos.dreamer_v1.loss import gaussian_independent, reconstruction_loss
from sheeprl_tpu.algos.dreamer_v1.utils import (
    compute_lambda_values,
    normalize_obs_jnp,
    prepare_obs,
    test,
)
from sheeprl_tpu.algos.p2e_dv1.agent import apply_ensemble, build_agent, build_player_fns
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.distributions import Bernoulli, Independent, Normal
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.plane import train_gated_burst_plan
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import learn_probes, log_sps_metrics, probes_enabled, profile_tick, span
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.train import build_train_burst, metric_fetch_gate, run_train_burst
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs

sg = jax.lax.stop_gradient


def build_train_fn(
    world_model: WorldModel,
    actor: Actor,
    critic,
    ensemble_member,
    txs: Dict[str, optax.GradientTransformation],
    cfg,
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
):
    """``train_step(agent_state, data, key) -> (agent_state, metrics)``."""
    axis = fabric.data_axis
    cnn_keys = tuple(cfg.cnn_keys.encoder)
    mlp_keys = tuple(cfg.mlp_keys.encoder)
    learn_on = probes_enabled(cfg)
    learn_clips = {name: clip_norm_of(tx) for name, tx in txs.items()}
    wm_cfg = cfg.algo.world_model
    stoch_size = int(wm_cfg.stochastic_size)
    rec_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    use_continues = bool(wm_cfg.use_continues)
    intrinsic_mult = float(cfg.algo.intrinsic_reward_multiplier)
    distribution = resolve_actor_distribution(
        cfg.distribution.get("type", "auto"), is_continuous
    )
    init_std = float(cfg.algo.actor.init_std)
    min_std = float(cfg.algo.actor.min_std)

    def wm_apply(params, method, *args):
        return world_model.apply({"params": params}, *args, method=method)

    # -- world model loss: identical to DV1, but the embeddings are also
    # returned for ensemble training (reference :200-222) ------------------

    def wm_loss_fn(wm_params, data, key):
        T, B = data["rewards"].shape[:2]
        batch_obs = {k: data[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k] for k in mlp_keys})
        embedded = wm_apply(wm_params, WorldModel.encode, batch_obs)

        def step(carry, inp):
            posterior, recurrent = carry
            action, embed, eps = inp
            recurrent, posterior, post_ms = world_model.apply(
                {"params": wm_params},
                posterior, recurrent, action, embed, None, eps,
                method=WorldModel.dynamic_posterior,
            )
            return (posterior, recurrent), (recurrent, posterior, post_ms)

        # pre-drawn sampling noise + batched prior stats (same as DV1/DV3)
        noise = jax.random.normal(key, (T, B, stoch_size))
        (_, _), (recurrents, posteriors, post_ms) = jax.lax.scan(
            step,
            (jnp.zeros((B, stoch_size)), jnp.zeros((B, rec_size))),
            (data["actions"], embedded, noise),
        )
        prior_ms = wm_apply(wm_params, WorldModel.prior_stats, recurrents)
        latents = jnp.concatenate([posteriors, recurrents], -1)
        recon = wm_apply(wm_params, WorldModel.decode, latents)
        qo = {k: gaussian_independent(recon[k], 1.0, 3 if k in cnn_keys else 1) for k in recon}
        qr = gaussian_independent(wm_apply(wm_params, WorldModel.reward, latents), 1.0, 1)
        if use_continues:
            qc = Independent(Bernoulli(logits=wm_apply(wm_params, WorldModel.continues, latents)), 1)
            continue_targets = 1.0 - data["dones"]
        else:
            qc = continue_targets = None
        posteriors_dist = Independent(Normal(post_ms[0], post_ms[1]), 1)
        priors_dist = Independent(Normal(prior_ms[0], prior_ms[1]), 1)
        loss, metrics = reconstruction_loss(
            qo, batch_obs, qr, data["rewards"],
            posteriors_dist, priors_dist,
            float(wm_cfg.kl_free_nats), float(wm_cfg.kl_regularizer),
            qc, continue_targets, float(wm_cfg.continue_scale_factor),
        )
        return loss, (metrics, sg(posteriors), sg(recurrents), sg(embedded))

    # -- ensemble loss (reference :200-222) --------------------------------

    def ensemble_loss_fn(ens_params, posteriors, recurrents, actions, embedded):
        inp = jnp.concatenate([posteriors, recurrents, actions], -1)
        out = apply_ensemble(ensemble_member, ens_params, inp)[:, :-1]
        target = embedded[1:][None]
        dist = Independent(Normal(out, jnp.ones_like(out)), 1)
        return -jnp.sum(jnp.mean(dist.log_prob(target), axis=tuple(range(1, out.ndim - 1))))

    # -- DV1 imagination with recorded actions (reference :224-245) --------

    def imagination_rollout(wm_params, actor_params, posteriors, recurrents, key):
        prior = posteriors.reshape(-1, stoch_size)
        recurrent = recurrents.reshape(-1, rec_size)
        latent = jnp.concatenate([prior, recurrent], -1)

        def policy(latent, k):
            pre = actor.apply({"params": actor_params}, sg(latent))
            dists = build_actor_dists(pre, is_continuous, distribution, init_std, min_std, unimix=0.0)
            return jnp.concatenate(sample_actor_actions(dists, is_continuous, k, True), -1)

        def step(carry, inp):
            prior, recurrent, latent = carry
            eps_img, k_act = inp
            action = policy(latent, k_act)
            prior, recurrent = world_model.apply(
                {"params": wm_params}, prior, recurrent, action, None, eps_img,
                method=WorldModel.imagination,
            )
            latent = jnp.concatenate([prior, recurrent], -1)
            return (prior, recurrent, latent), (latent, action)

        k_eps, key = jax.random.split(key)
        noise = jax.random.normal(k_eps, (horizon, prior.shape[0], stoch_size))
        keys = jax.random.split(key, horizon)
        _, (latents, acts) = jax.lax.scan(step, (prior, recurrent, latent), (noise, keys))
        return latents, acts

    # -- shared behaviour-learning actor loss (reference :224-330 / :332-390)

    def behaviour_actor_loss(actor_params, wm_params, critic_params,
                             posteriors, recurrents, key, reward_fn):
        traj, imagined_actions = imagination_rollout(
            wm_params, actor_params, posteriors, recurrents, key
        )
        predicted_values = critic.apply({"params": critic_params}, traj)
        reward = reward_fn(traj, imagined_actions)
        if use_continues:
            continues = jax.nn.sigmoid(wm_apply(wm_params, WorldModel.continues, traj)) * gamma
        else:
            continues = jnp.ones_like(sg(reward)) * gamma

        lambda_values = compute_lambda_values(
            reward, predicted_values, continues,
            last_values=predicted_values[-1], lmbda=lmbda,
        )
        discount = sg(
            jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], 0), 0)
        )
        policy_loss = -jnp.mean(discount * lambda_values)
        aux = {
            "trajectories": sg(traj),
            "lambda_values": sg(lambda_values),
            "discount": discount,
            "reward_mean": jnp.mean(sg(reward)),
            "values_mean": jnp.mean(sg(predicted_values)),
        }
        return policy_loss, aux

    def critic_loss_fn(critic_params, traj, lambda_values, discount):
        qv = Independent(Normal(critic.apply({"params": critic_params}, traj[:-1]), 1.0), 1)
        return -jnp.mean(discount[..., 0] * qv.log_prob(lambda_values))

    # ----------------------------------------------------------------------

    def local_step(agent_state, data, key):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        params = agent_state["params"]
        opt = agent_state["opt"]

        k_wm, k_expl, k_task = jax.random.split(key, 3)

        (wm_loss, (wm_metrics, posteriors, recurrents, embedded)), wm_grads = jax.value_and_grad(
            wm_loss_fn, has_aux=True
        )(params["world_model"], data, k_wm)
        wm_grads = pmean(wm_grads, axis)
        wm_updates, wm_opt = txs["world_model"].update(wm_grads, opt["world_model"], params["world_model"])
        wm_params = optax.apply_updates(params["world_model"], wm_updates)

        ens_loss, ens_grads = jax.value_and_grad(ensemble_loss_fn)(
            params["ensembles"], posteriors, recurrents, data["actions"], embedded
        )
        ens_grads = pmean(ens_grads, axis)
        ens_updates, ens_opt = txs["ensembles"].update(ens_grads, opt["ensembles"], params["ensembles"])
        ens_params = optax.apply_updates(params["ensembles"], ens_updates)

        def intrinsic_reward_fn(traj, imagined_actions):
            ens_in = jnp.concatenate([sg(traj), sg(imagined_actions)], -1)
            pred = apply_ensemble(ensemble_member, ens_params, ens_in)
            return jnp.var(pred, axis=0).mean(-1, keepdims=True) * intrinsic_mult

        def extrinsic_reward_fn(traj, imagined_actions):
            del imagined_actions
            return wm_apply(wm_params, WorldModel.reward, traj)

        # exploration actor + critic
        (pl_expl, aux_expl), a_expl_grads = jax.value_and_grad(
            behaviour_actor_loss, has_aux=True
        )(
            params["actor_exploration"], wm_params, params["critic_exploration"],
            posteriors, recurrents, k_expl, intrinsic_reward_fn,
        )
        a_expl_grads = pmean(a_expl_grads, axis)
        a_expl_updates, a_expl_opt = txs["actor_exploration"].update(
            a_expl_grads, opt["actor_exploration"], params["actor_exploration"]
        )
        actor_expl_params = optax.apply_updates(params["actor_exploration"], a_expl_updates)

        ce_loss, ce_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic_exploration"],
            aux_expl["trajectories"], aux_expl["lambda_values"], aux_expl["discount"],
        )
        ce_grads = pmean(ce_grads, axis)
        ce_updates, ce_opt = txs["critic_exploration"].update(
            ce_grads, opt["critic_exploration"], params["critic_exploration"]
        )
        critic_expl_params = optax.apply_updates(params["critic_exploration"], ce_updates)

        # task actor + critic
        (pl_task, aux_task), a_task_grads = jax.value_and_grad(
            behaviour_actor_loss, has_aux=True
        )(
            params["actor_task"], wm_params, params["critic_task"],
            posteriors, recurrents, k_task, extrinsic_reward_fn,
        )
        a_task_grads = pmean(a_task_grads, axis)
        a_task_updates, a_task_opt = txs["actor_task"].update(
            a_task_grads, opt["actor_task"], params["actor_task"]
        )
        actor_task_params = optax.apply_updates(params["actor_task"], a_task_updates)

        ct_loss, ct_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic_task"],
            aux_task["trajectories"], aux_task["lambda_values"], aux_task["discount"],
        )
        ct_grads = pmean(ct_grads, axis)
        ct_updates, ct_opt = txs["critic_task"].update(ct_grads, opt["critic_task"], params["critic_task"])
        critic_task_params = optax.apply_updates(params["critic_task"], ct_updates)

        metrics = dict(wm_metrics)
        metrics["Loss/ensemble_loss"] = ens_loss
        metrics["Loss/policy_loss_exploration"] = pl_expl
        metrics["Loss/value_loss_exploration"] = ce_loss
        metrics["Loss/policy_loss_task"] = pl_task
        metrics["Loss/value_loss_task"] = ct_loss
        metrics["Rewards/intrinsic"] = aux_expl["reward_mean"]
        metrics["Values_exploration/predicted_values"] = aux_expl["values_mean"]
        metrics["Values_exploration/lambda_values"] = jnp.mean(aux_expl["lambda_values"])
        metrics["Grads/world_model"] = optax.global_norm(wm_grads)
        metrics["Grads/ensemble"] = optax.global_norm(ens_grads)
        metrics["Grads/actor_exploration"] = optax.global_norm(a_expl_grads)
        metrics["Grads/critic_exploration"] = optax.global_norm(ce_grads)
        metrics["Grads/actor_task"] = optax.global_norm(a_task_grads)
        metrics["Grads/critic_task"] = optax.global_norm(ct_grads)
        metrics = pmean(metrics, axis)
        if learn_on:
            # grads are already pmean'd, so the probe scalars are identical
            # on every shard — the learn plane adds no collectives
            metrics.update(
                learn_probes(
                    {
                        "world_model": wm_grads,
                        "ensembles": ens_grads,
                        "actor_exploration": a_expl_grads,
                        "critic_exploration": ce_grads,
                        "actor_task": a_task_grads,
                        "critic_task": ct_grads,
                    },
                    params={
                        "world_model": params["world_model"],
                        "ensembles": params["ensembles"],
                        "actor_exploration": params["actor_exploration"],
                        "critic_exploration": params["critic_exploration"],
                        "actor_task": params["actor_task"],
                        "critic_task": params["critic_task"],
                    },
                    updates={
                        "world_model": wm_updates,
                        "ensembles": ens_updates,
                        "actor_exploration": a_expl_updates,
                        "critic_exploration": ce_updates,
                        "actor_task": a_task_updates,
                        "critic_task": ct_updates,
                    },
                    losses=(wm_loss, ens_loss, pl_expl, ce_loss, pl_task, ct_loss),
                    clip_norms=learn_clips,
                )
            )

        new_state = {
            "params": {
                "world_model": wm_params,
                "actor_task": actor_task_params,
                "critic_task": critic_task_params,
                "actor_exploration": actor_expl_params,
                "critic_exploration": critic_expl_params,
                "ensembles": ens_params,
            },
            "opt": {
                "world_model": wm_opt,
                "ensembles": ens_opt,
                "actor_task": a_task_opt,
                "critic_task": ct_opt,
                "actor_exploration": a_expl_opt,
                "critic_exploration": ce_opt,
            },
        }
        return new_state, metrics

    # step + fused-burst programs (scanned per-step input: key); the
    # ensemble params/optimizer state ride the burst carry with the rest
    return build_train_burst(local_step, fabric, n_scanned=1)


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    cfg.algo.player.actor_type = "exploration"
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # each env fault-tolerant via RestartOnException; vector backend
    # picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir, restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.cnn_keys.encoder == [] and cfg.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    root_key, build_key = jax.random.split(root_key)
    world_model, actor, critic, ensemble_member, params = build_agent(
        cfg, actions_dim, is_continuous, observation_space, build_key
    )
    txs = {
        "world_model": instantiate(
            cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients
        ),
        "ensembles": instantiate(
            cfg.algo.ensembles.optimizer, max_grad_norm=cfg.algo.ensembles.clip_gradients
        ),
        "actor_task": instantiate(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients),
        "critic_task": instantiate(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients),
        "actor_exploration": instantiate(
            cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients
        ),
        "critic_exploration": instantiate(
            cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients
        ),
    }
    agent_state = {
        "params": params,
        "opt": {
            "world_model": txs["world_model"].init(params["world_model"]),
            "ensembles": txs["ensembles"].init(params["ensembles"]),
            "actor_task": txs["actor_task"].init(params["actor_task"]),
            "critic_task": txs["critic_task"].init(params["critic_task"]),
            "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
            "critic_exploration": txs["critic_exploration"].init(params["critic_exploration"]),
        },
    }

    expl_decay_steps = 0
    state = None
    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "expl_decay_steps": 0,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        expl_decay_steps = int(np.asarray(state["expl_decay_steps"]))
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(agent_state, fabric.replicated)

    train_fn = build_train_fn(
        world_model, actor, critic, ensemble_member, txs, cfg, fabric, actions_dim, is_continuous
    )
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)

    # host-mirrored acting snapshots (utils/host.py)
    wm_mirror = HostParamMirror.from_cfg(agent_state["params"]["world_model"], fabric, cfg)
    actor_expl_mirror = HostParamMirror.from_cfg(
        agent_state["params"]["actor_exploration"], fabric, cfg
    )
    actor_task_mirror = HostParamMirror.from_cfg(
        agent_state["params"]["actor_task"], fabric, cfg
    )
    play_wm = wm_mirror(agent_state["params"]["world_model"])
    play_actor_expl = actor_expl_mirror(agent_state["params"]["actor_exploration"])
    play_actor_task = actor_task_mirror(agent_state["params"]["actor_task"])

    def player_actor_params():
        if cfg.algo.player.actor_type == "exploration":
            return play_actor_expl
        return play_actor_task

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        kind="sequential",
        obs_keys=obs_keys,
        min_size=8,
        dry_run_size=8,
    )
    if state is not None and cfg.buffer.get("checkpoint", False) and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    updates_before_training = (
        cfg.algo.train_every // policy_steps_per_update if not cfg.dry_run else 0
    )
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.checkpoint:
        learning_starts += start_step
    max_step_expl_decay = cfg.algo.actor.max_step_expl_decay // (
        cfg.algo.per_rank_gradient_steps * world_size
    ) if cfg.algo.actor.max_step_expl_decay else 0
    expl_amount = float(cfg.algo.actor.expl_amount)
    if cfg.checkpoint.resume_from:
        expl_amount = polynomial_decay(
            expl_decay_steps,
            initial=cfg.algo.actor.expl_amount,
            final=cfg.algo.actor.expl_min,
            max_decay_steps=max_step_expl_decay,
        )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True, double-buffered host prefetch otherwise; the
    # whole [n, L, B, ...] burst arrives on device in one step, and the
    # per-gradient-step loop below slices device arrays (no H2D per step)
    staging = make_replay_staging(
        cfg,
        fabric,
        rb,
        sequence_length=int(cfg.per_rank_sequence_length),
        batch_sharding=fabric.sharding(None, None, fabric.data_axis),
        seed=cfg.seed,
    )
    rb = staging.rb

    o = envs.reset(seed=cfg.seed)[0]
    obs = prepare_obs(o, cnn_keys, mlp_keys, n_envs)
    step_data = {k: obs[k][None] for k in obs_keys}
    step_data["dones"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["actions"] = np.zeros((1, n_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, n_envs, 1), np.float32)
    rb.add(step_data)
    player_state = player_fns["init_states"](play_wm, n_envs)

    per_rank_gradient_steps = 0

    # Burst acting (tier b, howto/rollout_engine.md): K env steps per device
    # dispatch, K = env.act_burst; 1 reproduces the per-step path exactly.
    # The RSSM player state rides the burst carry next to the observation —
    # the host callback is the whole old loop body (env step, episode
    # bookkeeping, buffer adds) and applies episode resets with the same
    # (1 - mask) * state arithmetic the jitted reset path computes, so
    # trajectories do not depend on K. The acting actor (exploration vs
    # task, cfg.algo.player.actor_type) is a rollout() parameter.
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    n_sub = len(actions_dim)
    state_box = {
        "carry": {
            "obs": obs,
            "player": {k: np.asarray(v) for k, v in player_state.items()},
        },
        "policy_step": policy_step,
    }

    def _host_step_core(actions, real_actions, player_np):
        state_box["policy_step"] += n_envs
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            o, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated).astype(np.float32)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        next_obs_np = {k: np.asarray(o[k]) for k in o}
        dones_idxes = np.nonzero(dones.reshape(-1))[0].tolist()
        real_next_obs = {k: v.copy() for k, v in next_obs_np.items()}
        if "final_obs" in infos and len(dones_idxes) > 0:
            for idx in dones_idxes:
                fo = infos["final_obs"][idx]
                if fo is not None:
                    for k in real_next_obs:
                        if k in fo:
                            real_next_obs[k][idx] = np.asarray(fo[k])

        obs_row = prepare_obs(real_next_obs, cnn_keys, mlp_keys, n_envs)
        for k in obs_keys:
            step_data[k] = obs_row[k][None]
        rewards = np.asarray(rewards, np.float32).reshape(n_envs, 1)
        step_data["dones"] = dones.reshape(1, n_envs, 1)
        step_data["actions"] = actions.reshape(1, n_envs, -1).astype(np.float32)
        step_data["rewards"] = clip_rewards_fn(rewards)[None]
        rb.add(step_data)

        new_obs = prepare_obs(next_obs_np, cnn_keys, mlp_keys, n_envs)

        if len(dones_idxes) > 0:
            reset_obs = prepare_obs(
                {k: next_obs_np[k][dones_idxes] for k in next_obs_np},
                cnn_keys, mlp_keys, len(dones_idxes),
            )
            reset_data = {k: reset_obs[k][None] for k in obs_keys}
            reset_data["dones"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            rb.add(reset_data, dones_idxes)

            step_data["dones"][:, dones_idxes] = 0.0
            reset_mask = np.zeros((n_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            # same arithmetic as player_fns["reset_states"], applied host-side
            keep = np.float32(1.0) - reset_mask
            player_np = {k: keep * v for k, v in player_np.items()}

        carry = {"obs": new_obs, "player": player_np}
        state_box["carry"] = carry
        return carry

    def _host_env_step(*args):
        actions_j = [np.asarray(a) for a in args[:n_sub]]
        player_np = {
            "actions": np.asarray(args[n_sub]),
            "recurrent": np.asarray(args[n_sub + 1]),
            "stochastic": np.asarray(args[n_sub + 2]),
        }
        actions = np.concatenate(actions_j, -1)
        if is_continuous:
            real_actions = actions
        else:
            real_actions = np.stack([np.argmax(a, axis=-1) for a in actions_j], axis=-1)
        return _host_step_core(actions, real_actions, player_np)

    def _act_fn(p, carry, key):
        # the key advances inside the jitted burst with the same split order
        # the per-step loop used (carried key first, act key second), so the
        # K=1 key stream is bitwise the per-step stream
        key, act_key = jax.random.split(key)
        norm_obs = normalize_obs_jnp(carry["obs"], cnn_keys)
        actions_j, new_player = player_fns["exploration_action"](
            p["wm"], p["actor"], carry["player"], norm_obs, act_key, p["expl"]
        )
        cb_args = tuple(actions_j) + (
            new_player["actions"],
            new_player["recurrent"],
            new_player["stochastic"],
        )
        return cb_args, key

    burst_actor = BurstActor(_act_fn, _host_env_step, state_box["carry"])

    # in-run eval (howto/evaluation.md): rank 0 publishes the frozen params
    # through the policy channel every eval.every_n_steps; a separate process
    # scores them (the task actor — the eval builder picks actor_task), so
    # nothing below touches the train-step critical path
    from sheeprl_tpu.evals.inrun import maybe_start_inrun_eval

    inrun = maybe_start_inrun_eval(fabric, cfg, log_dir)

    update = start_step
    while update <= num_updates:
        n_act, random_phase = train_gated_burst_plan(
            update,
            act_burst,
            learning_starts,
            num_updates,
            updates_before_training,
            resuming=cfg.checkpoint.resume_from is not None,
        )
        if random_phase:
            real_actions = actions = np.array(envs.action_space.sample())
            if not is_continuous:
                actions = np.concatenate(
                    [
                        np.eye(act_dim, dtype=np.float32)[act]
                        for act, act_dim in zip(
                            actions.reshape(len(actions_dim), -1), actions_dim
                        )
                    ],
                    axis=-1,
                )
            _host_step_core(actions, real_actions, state_box["carry"]["player"])
        else:
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, root_key = burst_actor.rollout(
                    {
                        "wm": play_wm,
                        "actor": player_actor_params(),
                        "expl": jnp.float32(expl_amount),
                    },
                    state_box["carry"],
                    root_key,
                    n_act,
                )
            # the burst program commits its inputs to the player's device;
            # pull the carried key back to host numpy (uncommitted) so the
            # possibly multi-device train program keeps accepting it
            root_key = np.asarray(root_key)
        policy_step = state_box["policy_step"]

        update += n_act
        last = update - 1
        updates_before_training -= n_act

        if last >= learning_starts and updates_before_training <= 0:
            n_samples = cfg.algo.per_rank_gradient_steps
            metrics = None
            if n_samples > 0:
                local_data = staging.sample_device(
                    cfg.per_rank_batch_size * world_size,
                    sequence_length=cfg.per_rank_sequence_length,
                    n_samples=n_samples,
                )
                # metrics are pulled at most once per burst behind the
                # shared fetch gate (sheeprl_tpu/train)
                fetch_metrics = metric_fetch_gate(
                    cfg,
                    aggregator,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    update=last,
                    num_updates=num_updates,
                    policy_steps_per_update=policy_steps_per_update,
                    world_size=world_size,
                )
                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    # the whole burst (n_samples gradient steps) is ONE
                    # scanned dispatch (sheeprl_tpu/train): per-call overhead
                    # on a remote-attached device would otherwise repeat per
                    # gradient step
                    root_key, train_key = jax.random.split(root_key)
                    agent_state, metrics, _ = run_train_burst(
                        train_fn,
                        agent_state,
                        local_data,
                        (jax.random.split(train_key, n_samples),),
                        world_size=world_size,
                        fetch_metrics=fetch_metrics,
                    )
                    per_rank_gradient_steps += n_samples
                    play_wm = wm_mirror(agent_state["params"]["world_model"])
                    play_actor_expl = actor_expl_mirror(agent_state["params"]["actor_exploration"])
                    play_actor_task = actor_task_mirror(agent_state["params"]["actor_task"])
                    train_step += world_size
            updates_before_training = cfg.algo.train_every // policy_steps_per_update
            if cfg.algo.actor.expl_decay:
                expl_decay_steps += 1
                expl_amount = polynomial_decay(
                    expl_decay_steps,
                    initial=cfg.algo.actor.expl_amount,
                    final=cfg.algo.actor.expl_min,
                    max_decay_steps=max_step_expl_decay,
                )
            if aggregator and not aggregator.disabled:
                if metrics is not None:
                    for k, v in metrics.items():
                        if k in aggregator:
                            aggregator.update(k, float(np.asarray(v)))
                if "Params/exploration_amount" in aggregator:
                    aggregator.update("Params/exploration_amount", expl_amount)

        if inrun is not None and last >= learning_starts and inrun.due(policy_step):
            # versioned by policy_step; the npz write runs on the publisher's
            # writer thread, so the cost here is one params-sized device_get
            inrun.maybe_publish(
                policy_step,
                {"agent": {"params": jax.device_get(agent_state["params"])}},
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "expl_decay_steps": expl_decay_steps,
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    if inrun is not None:
        inrun.close()
    staging.close()
    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        final = jax.device_get(agent_state["params"])
        test(
            player_fns,
            {"world_model": final["world_model"], "actor": final["actor_task"]},
            fabric, cfg, log_dir, sample_actions=False,
            normalize_fn=normalize_obs_jnp,
        )
