"""Plan2Explore-DV1 agent (reference ``sheeprl/algos/p2e_dv1/agent.py``
build_agent :30-196 and the ensemble construction in
``p2e_dv1_exploration.py:430-470``).

DV1 chassis + the P2E additions: a vmapped ensemble predicting the next
**observation embedding** (the encoder output — unlike V2/V3, which predict
the next stochastic state), a dual actor, and an exploration critic (no
target critics anywhere in V1).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.agent import (
    Actor,
    MLPHead,
    WorldModel,
    build_player_fns,  # noqa: F401
)
from sheeprl_tpu.algos.dreamer_v2.agent import (
    cnn_encoder_output_dim,
    xavier_normal_initialization,
)
from sheeprl_tpu.algos.p2e_dv3.agent import (  # noqa: F401
    EnsembleMember,
    apply_ensemble,
    init_ensemble,
)


def embedding_dim(cfg, cnn_keys, mlp_keys) -> int:
    """Static size of the encoder output (cnn features ‖ mlp features)."""
    dim = 0
    if cnn_keys:
        dim += cnn_encoder_output_dim(
            (int(cfg.env.screen_size), int(cfg.env.screen_size)),
            int(cfg.algo.world_model.encoder.cnn_channels_multiplier),
        )
    if mlp_keys:
        dim += int(cfg.algo.world_model.encoder.dense_units)
    return dim


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    observation_space,
    key: jax.Array,
) -> Tuple[WorldModel, Actor, MLPHead, EnsembleMember, Dict[str, Any]]:
    """Returns ``(world_model, actor, critic, ensemble_member, params)`` with
    ``params = {world_model, actor_task, critic_task, actor_exploration,
    critic_exploration, ensembles}``."""
    from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as dv1_build_agent

    k_dv1, k_expl_actor, k_expl_critic, k_ens, k_xa, k_xc = jax.random.split(key, 6)
    world_model, actor, critic, dv1_params = dv1_build_agent(
        cfg, actions_dim, is_continuous, observation_space, k_dv1
    )
    wm_cfg = cfg.algo.world_model
    latent_size = int(wm_cfg.stochastic_size) + int(wm_cfg.recurrent_model.recurrent_state_size)
    act_dim = int(np.sum(actions_dim))
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)

    actor_expl_params = xavier_normal_initialization(
        actor.init(k_expl_actor, jnp.zeros((1, latent_size)))["params"], k_xa
    )
    critic_expl_params = xavier_normal_initialization(
        critic.init(k_expl_critic, jnp.zeros((1, latent_size)))["params"], k_xc
    )

    ens_cfg = cfg.algo.ensembles
    ensemble_member = EnsembleMember(
        output_dim=embedding_dim(cfg, cnn_keys, mlp_keys),
        mlp_layers=int(ens_cfg.mlp_layers),
        dense_units=int(ens_cfg.dense_units),
        layer_norm=bool(ens_cfg.get("layer_norm", False)),
        activation=ens_cfg.dense_act,
    )
    ensembles = init_ensemble(ensemble_member, int(ens_cfg.n), latent_size + act_dim, k_ens)

    params = {
        "world_model": dv1_params["world_model"],
        "actor_task": dv1_params["actor"],
        "critic_task": dv1_params["critic"],
        "actor_exploration": actor_expl_params,
        "critic_exploration": critic_expl_params,
        "ensembles": ensembles,
    }
    return world_model, actor, critic, ensemble_member, params
