"""P2E-DV1 evaluation (reference ``sheeprl/algos/p2e_dv1/evaluate.py``):
registered for both phases; always evaluates the **task** actor."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.utils import normalize_obs_jnp, test
from sheeprl_tpu.algos.p2e_dv1.agent import build_agent, build_player_fns
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_evaluation(algorithms=["p2e_dv1_exploration", "p2e_dv1_finetuning"])
def evaluate_p2e_dv1(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))

    env = make_eval_env(cfg, log_dir)
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    env.close()

    world_model, actor, critic, _, _ = build_agent(
        cfg, actions_dim, is_continuous, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    params = params_on_device(state["agent"]["params"])
    actor_params = params.get("actor_task", params.get("actor"))
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)
    test(
        player_fns,
        {"world_model": params["world_model"], "actor": actor_params},
        fabric, cfg, log_dir, normalize_fn=normalize_obs_jnp,
    )
