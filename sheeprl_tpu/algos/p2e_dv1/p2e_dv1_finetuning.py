"""Plan2Explore-DV1, finetuning phase (reference
``sheeprl/algos/p2e_dv1/p2e_dv1_finetuning.py`` :28-460): reload the
exploration checkpoint, inherit model hyper-parameters from the exploration
config (done by the CLI), train with the plain DV1 step, and switch the
player from the exploration actor to the task actor at ``learning_starts``.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import build_train_fn
from sheeprl_tpu.train import metric_fetch_gate, run_train_burst
from sheeprl_tpu.algos.dreamer_v1.utils import normalize_obs_jnp, prepare_obs, test
from sheeprl_tpu.algos.p2e_dv1.agent import build_agent, build_player_fns
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.plane import train_gated_burst_plan
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import log_sps_metrics, profile_tick, span
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


def _as_jnp_tree(tree):
    return jax.tree_util.tree_map(jnp.asarray, tree)


@register_algorithm()
def main(fabric, cfg: Dict[str, Any], exploration_cfg: Dict[str, Any]):
    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    resume_from_checkpoint = bool(cfg.checkpoint.resume_from)
    ckpt_path = cfg.checkpoint.resume_from or cfg.checkpoint.exploration_ckpt_path
    state = fabric.load(ckpt_path)

    for k in ("gamma", "lmbda", "horizon", "dense_units", "mlp_layers",
              "dense_act", "cnn_act"):
        cfg.algo[k] = exploration_cfg.algo[k]
    cfg.algo.world_model = exploration_cfg.algo.world_model
    cfg.algo.actor = exploration_cfg.algo.actor
    cfg.algo.critic = exploration_cfg.algo.critic
    cfg.algo.ensembles = exploration_cfg.algo.ensembles
    cfg.env.clip_rewards = exploration_cfg.env.clip_rewards
    cfg.cnn_keys = exploration_cfg.cnn_keys
    cfg.mlp_keys = exploration_cfg.mlp_keys
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1
    if cfg.buffer.get("load_from_exploration", False) and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs
    if resume_from_checkpoint:
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size

    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # each env fault-tolerant via RestartOnException; vector backend
    # picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir, restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    root_key, build_key = jax.random.split(root_key)
    world_model, actor, critic, _, _ = build_agent(
        cfg, actions_dim, is_continuous, observation_space, build_key
    )

    if resume_from_checkpoint:
        params = _as_jnp_tree(state["agent"]["params"])
        actor_expl_params = _as_jnp_tree(state["actor_exploration"])
        expl_decay_steps = int(np.asarray(state["expl_decay_steps"]))
    else:
        expl = state["agent"]["params"]
        params = _as_jnp_tree(
            {
                "world_model": expl["world_model"],
                "actor": expl["actor_task"],
                "critic": expl["critic_task"],
            }
        )
        actor_expl_params = _as_jnp_tree(expl["actor_exploration"])
        expl_decay_steps = int(np.asarray(state["expl_decay_steps"]))

    world_tx = instantiate(
        cfg.algo.world_model.optimizer, max_grad_norm=cfg.algo.world_model.clip_gradients
    )
    actor_tx = instantiate(cfg.algo.actor.optimizer, max_grad_norm=cfg.algo.actor.clip_gradients)
    critic_tx = instantiate(cfg.algo.critic.optimizer, max_grad_norm=cfg.algo.critic.clip_gradients)
    agent_state = {
        "params": params,
        "opt": {
            "world_model": world_tx.init(params["world_model"]),
            "actor": actor_tx.init(params["actor"]),
            "critic": critic_tx.init(params["critic"]),
        },
    }
    if resume_from_checkpoint:
        from sheeprl_tpu.utils.utils import conform_pytree

        agent_state["opt"] = _as_jnp_tree(
            conform_pytree(jax.device_get(agent_state["opt"]), state["agent"]["opt"])
        )
    agent_state = jax.device_put(agent_state, fabric.replicated)
    actor_expl_params = jax.device_put(actor_expl_params, fabric.replicated)

    train_fn = build_train_fn(
        world_model, actor, critic, world_tx, actor_tx, critic_tx,
        cfg, fabric, actions_dim, is_continuous,
    )
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)
    # host-mirrored acting snapshots (utils/host.py); the frozen
    # exploration actor is mirrored once
    wm_mirror = HostParamMirror.from_cfg(agent_state["params"]["world_model"], fabric, cfg)
    actor_mirror = HostParamMirror.from_cfg(agent_state["params"]["actor"], fabric, cfg)
    play_wm = wm_mirror(agent_state["params"]["world_model"])
    play_actor = actor_mirror(agent_state["params"]["actor"])
    play_actor_expl = HostParamMirror.from_cfg(actor_expl_params, fabric, cfg)(
        actor_expl_params
    )

    player_actor_type = str(cfg.algo.player.actor_type)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        kind="sequential",
        obs_keys=obs_keys,
        min_size=8,
        dry_run_size=8,
    )
    if "rb" in state and (
        (resume_from_checkpoint and cfg.buffer.get("checkpoint", False))
        or (not resume_from_checkpoint and cfg.buffer.get("load_from_exploration", False))
    ):
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_step = int(np.asarray(state["update"])) // world_size if resume_from_checkpoint else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if resume_from_checkpoint else 0
    last_log = int(np.asarray(state["last_log"])) if resume_from_checkpoint else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if resume_from_checkpoint else 0
    policy_steps_per_update = int(n_envs)
    updates_before_training = (
        cfg.algo.train_every // policy_steps_per_update if not cfg.dry_run else 0
    )
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if resume_from_checkpoint and not cfg.buffer.checkpoint:
        learning_starts += start_step
    max_step_expl_decay = cfg.algo.actor.max_step_expl_decay // (
        cfg.algo.per_rank_gradient_steps * world_size
    ) if cfg.algo.actor.max_step_expl_decay else 0
    expl_amount = float(cfg.algo.actor.expl_amount)
    if resume_from_checkpoint:
        expl_amount = polynomial_decay(
            expl_decay_steps,
            initial=cfg.algo.actor.expl_amount,
            final=cfg.algo.actor.expl_min,
            max_decay_steps=max_step_expl_decay,
        )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_update != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_update value ({policy_steps_per_update})."
        )
    warn_checkpoint_rounding(cfg, policy_steps_per_update)

    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True, double-buffered host prefetch otherwise; the
    # whole [n, L, B, ...] burst arrives on device in one step, and the
    # per-gradient-step loop below slices device arrays (no H2D per step)
    staging = make_replay_staging(
        cfg,
        fabric,
        rb,
        sequence_length=int(cfg.per_rank_sequence_length),
        batch_sharding=fabric.sharding(None, None, fabric.data_axis),
        seed=cfg.seed,
    )
    rb = staging.rb

    o = envs.reset(seed=cfg.seed)[0]
    obs = prepare_obs(o, cnn_keys, mlp_keys, n_envs)
    step_data = {k: obs[k][None] for k in obs_keys}
    step_data["dones"] = np.zeros((1, n_envs, 1), np.float32)
    step_data["actions"] = np.zeros((1, n_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, n_envs, 1), np.float32)
    rb.add(step_data)
    player_state = player_fns["init_states"](play_wm, n_envs)


    def player_actor_params():
        if player_actor_type == "exploration":
            return play_actor_expl
        return play_actor

    per_rank_gradient_steps = 0

    # Burst acting (tier b, howto/rollout_engine.md): K env steps per device
    # dispatch, K = env.act_burst; 1 reproduces the per-step path exactly.
    # The RSSM player state rides the burst carry next to the observation.
    # The finetuning wrinkle is the actor switch: the player acts with the
    # frozen exploration actor until ``learning_starts``, then with the task
    # actor — the switch is re-checked once per burst and the burst plan is
    # clamped so no burst ever spans it.
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)
    n_sub = len(actions_dim)
    state_box = {
        "carry": {
            "obs": obs,
            "player": {k: np.asarray(v) for k, v in player_state.items()},
        },
        "policy_step": policy_step,
    }

    def _host_step_core(actions, real_actions, player_np):
        state_box["policy_step"] += n_envs
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            o, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated).astype(np.float32)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        next_obs_np = {k: np.asarray(o[k]) for k in o}
        dones_idxes = np.nonzero(dones.reshape(-1))[0].tolist()
        real_next_obs = {k: v.copy() for k, v in next_obs_np.items()}
        if "final_obs" in infos and len(dones_idxes) > 0:
            for idx in dones_idxes:
                fo = infos["final_obs"][idx]
                if fo is not None:
                    for k in real_next_obs:
                        if k in fo:
                            real_next_obs[k][idx] = np.asarray(fo[k])

        obs_row = prepare_obs(real_next_obs, cnn_keys, mlp_keys, n_envs)
        for k in obs_keys:
            step_data[k] = obs_row[k][None]
        rewards = np.asarray(rewards, np.float32).reshape(n_envs, 1)
        step_data["dones"] = dones.reshape(1, n_envs, 1)
        step_data["actions"] = actions.reshape(1, n_envs, -1).astype(np.float32)
        step_data["rewards"] = clip_rewards_fn(rewards)[None]
        rb.add(step_data)

        new_obs = prepare_obs(next_obs_np, cnn_keys, mlp_keys, n_envs)

        if len(dones_idxes) > 0:
            reset_obs = prepare_obs(
                {k: next_obs_np[k][dones_idxes] for k in next_obs_np},
                cnn_keys, mlp_keys, len(dones_idxes),
            )
            reset_data = {k: reset_obs[k][None] for k in obs_keys}
            reset_data["dones"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            rb.add(reset_data, dones_idxes)

            step_data["dones"][:, dones_idxes] = 0.0
            reset_mask = np.zeros((n_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            # same arithmetic as player_fns["reset_states"], applied host-side
            keep = np.float32(1.0) - reset_mask
            player_np = {k: keep * v for k, v in player_np.items()}

        carry = {"obs": new_obs, "player": player_np}
        state_box["carry"] = carry
        return carry

    def _host_env_step(*args):
        actions_j = [np.asarray(a) for a in args[:n_sub]]
        player_np = {
            "actions": np.asarray(args[n_sub]),
            "recurrent": np.asarray(args[n_sub + 1]),
            "stochastic": np.asarray(args[n_sub + 2]),
        }
        actions = np.concatenate(actions_j, -1)
        if is_continuous:
            real_actions = actions
        else:
            real_actions = np.stack([np.argmax(a, axis=-1) for a in actions_j], axis=-1)
        return _host_step_core(actions, real_actions, player_np)

    def _act_fn(p, carry, key):
        # the key advances inside the jitted burst with the same split order
        # the per-step loop used (carried key first, act key second), so the
        # K=1 key stream is bitwise the per-step stream
        key, act_key = jax.random.split(key)
        norm_obs = normalize_obs_jnp(carry["obs"], cnn_keys)
        actions_j, new_player = player_fns["exploration_action"](
            p["wm"], p["actor"], carry["player"], norm_obs, act_key, p["expl"]
        )
        cb_args = tuple(actions_j) + (
            new_player["actions"],
            new_player["recurrent"],
            new_player["stochastic"],
        )
        return cb_args, key

    burst_actor = BurstActor(_act_fn, _host_env_step, state_box["carry"])

    # in-run eval (howto/evaluation.md): rank 0 publishes the frozen params
    # through the policy channel every eval.every_n_steps; a separate process
    # scores the task actor, so nothing below touches the train-step
    # critical path
    from sheeprl_tpu.evals.inrun import maybe_start_inrun_eval

    inrun = maybe_start_inrun_eval(fabric, cfg, log_dir)

    update = start_step
    while update <= num_updates:
        # no random prefill here (resuming=True mirrors the per-step loop,
        # which acts with the frozen exploration actor from step one)
        n_act, _ = train_gated_burst_plan(
            update,
            act_burst,
            learning_starts,
            num_updates,
            updates_before_training,
            resuming=True,
        )
        if update < learning_starts:
            # the acting actor flips exploration → task at learning_starts;
            # clamp so the burst never spans the switch
            n_act = max(min(n_act, learning_starts - update), 1)
        if update >= learning_starts and player_actor_type == "exploration":
            player_actor_type = "task"

        with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
            _, root_key = burst_actor.rollout(
                {
                    "wm": play_wm,
                    "actor": player_actor_params(),
                    "expl": jnp.float32(expl_amount),
                },
                state_box["carry"],
                root_key,
                n_act,
            )
        # the burst program commits its inputs to the player's device;
        # pull the carried key back to host numpy (uncommitted) so the
        # possibly multi-device train program keeps accepting it
        root_key = np.asarray(root_key)
        policy_step = state_box["policy_step"]

        update += n_act
        last = update - 1
        updates_before_training -= n_act

        if last >= learning_starts and updates_before_training <= 0:
            n_samples = cfg.algo.per_rank_gradient_steps
            metrics = None
            if n_samples > 0:
                local_data = staging.sample_device(
                    cfg.per_rank_batch_size * world_size,
                    sequence_length=cfg.per_rank_sequence_length,
                    n_samples=n_samples,
                )
                # metrics are pulled at most once per burst behind the
                # shared fetch gate (sheeprl_tpu/train)
                fetch_metrics = metric_fetch_gate(
                    cfg,
                    aggregator,
                    policy_step=policy_step,
                    last_log=last_log,
                    train_step=train_step,
                    update=last,
                    num_updates=num_updates,
                    policy_steps_per_update=policy_steps_per_update,
                    world_size=world_size,
                )
                with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                    # the whole burst (n_samples gradient steps) is ONE
                    # scanned dispatch (sheeprl_tpu/train): per-call overhead
                    # on a remote-attached device would otherwise repeat per
                    # gradient step
                    root_key, train_key = jax.random.split(root_key)
                    agent_state, metrics, _ = run_train_burst(
                        train_fn,
                        agent_state,
                        local_data,
                        (jax.random.split(train_key, n_samples),),
                        world_size=world_size,
                        fetch_metrics=fetch_metrics,
                    )
                    per_rank_gradient_steps += n_samples
                    play_wm = wm_mirror(agent_state["params"]["world_model"])
                    play_actor = actor_mirror(agent_state["params"]["actor"])
                    train_step += world_size
            updates_before_training = cfg.algo.train_every // policy_steps_per_update
            if cfg.algo.actor.expl_decay:
                expl_decay_steps += 1
                expl_amount = polynomial_decay(
                    expl_decay_steps,
                    initial=cfg.algo.actor.expl_amount,
                    final=cfg.algo.actor.expl_min,
                    max_decay_steps=max_step_expl_decay,
                )
            if aggregator and not aggregator.disabled:
                if metrics is not None:
                    for k, v in metrics.items():
                        if k in aggregator:
                            aggregator.update(k, float(np.asarray(v)))
                if "Params/exploration_amount" in aggregator:
                    aggregator.update("Params/exploration_amount", expl_amount)

        if inrun is not None and last >= learning_starts and inrun.due(policy_step):
            # versioned by policy_step; the npz write runs on the publisher's
            # writer thread, so the cost here is one params-sized device_get
            inrun.maybe_publish(
                policy_step,
                {"agent": {"params": jax.device_get(agent_state["params"])}},
            )

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "actor_exploration": jax.device_get(actor_expl_params),
                "expl_decay_steps": expl_decay_steps,
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path_out = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path_out,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    if inrun is not None:
        inrun.close()
    staging.close()
    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        final = jax.device_get(agent_state["params"])
        test(
            player_fns,
            {"world_model": final["world_model"], "actor": final["actor"]},
            fabric, cfg, log_dir, sample_actions=False,
            normalize_fn=normalize_obs_jnp,
        )
