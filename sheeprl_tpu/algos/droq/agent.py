"""DroQ agent: dropout+LayerNorm Q ensemble (https://arxiv.org/abs/2110.02034).

Behavioral contract from the reference ``sheeprl/algos/droq/agent.py``
(DROQCritic :16-57: two-layer MLP with Dropout and LayerNorm on every hidden
layer; DROQAgent :60-210 reuses the SAC actor/alpha machinery, adds
``get_ith_q_value`` and per-critic target EMA).

TPU-native: the ensemble is stacked params under ``jax.vmap`` with one
dropout PRNG key per member, so all N dropout-perturbed Q evaluations run as
one batched program.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from sheeprl_tpu.models.models import MLP


class DROQCritic(nn.Module):
    """Q(s, a) with Dropout + LayerNorm hidden layers (reference :16-57)."""

    hidden_size: int = 256
    num_critics: int = 1
    dropout: float = 0.0

    @nn.compact
    def __call__(self, obs: jnp.ndarray, action: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=self.num_critics,
            activation="relu",
            layer_norm=True,
            dropout=self.dropout,
        )(x, deterministic=deterministic)


def init_droq_ensemble(critic: DROQCritic, key: jax.Array, n: int, obs_dim: int, act_dim: int) -> Any:
    dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: critic.init(k, dummy_obs, dummy_act)["params"])(keys)


def droq_ensemble_q(
    critic: DROQCritic,
    stacked_params: Any,
    obs: jnp.ndarray,
    action: jnp.ndarray,
    dropout_key: jax.Array = None,
) -> jnp.ndarray:
    """Ensemble Q → ``[batch, n]``; with a key, dropout is active and every
    member draws its own mask (the DroQ training regime)."""
    n = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if dropout_key is None:
        q = jax.vmap(lambda p: critic.apply({"params": p}, obs, action))(stacked_params)
    else:
        keys = jax.random.split(dropout_key, n)
        q = jax.vmap(
            lambda p, k: critic.apply(
                {"params": p}, obs, action, deterministic=False, rngs={"dropout": k}
            )
        )(stacked_params, keys)
    return jnp.moveaxis(q[..., 0], 0, -1)
