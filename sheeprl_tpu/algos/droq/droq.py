"""DroQ — SAC with a dropout-regularized Q ensemble and high replay ratio.

Behavioral contract from the reference ``sheeprl/algos/droq/droq.py``
(train :33-128, main :131-409): per update, ``per_rank_gradient_steps`` (20)
critic batches each update every ensemble member against a freshly sampled
dropout-perturbed TD target with a target-EMA after each member's step; the
actor and alpha update once per update from a *separate* batch, the actor
against the ensemble **mean** Q (reference :112 — not the min).

TPU-native notes (one jitted shard_map program per update, as in SAC):

- The reference steps each ensemble member with its own backward/step inside a
  Python loop (sharing one Adam across members, so each step also nudges the
  other members through stale momenta — an implementation quirk, not DroQ
  Algorithm 2). Here every member computes its loss with an independent
  dropout mask and the summed loss updates all members jointly; the target
  EMA runs once per gradient step, giving each member the same EMA cadence
  as the reference.
- Dropout keys thread through ``lax.scan`` so every gradient step and every
  member uses fresh masks, exactly one compiled program.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.droq.agent import DROQCritic, droq_ensemble_q, init_droq_ensemble
from sheeprl_tpu.algos.sac.agent import SACActor, action_bounds, squash_sample
from sheeprl_tpu.algos.sac.loss import entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import concat_obs, test
from sheeprl_tpu.ckpt import preemption_requested, should_checkpoint, warn_checkpoint_rounding
from sheeprl_tpu.config.instantiate import instantiate
from sheeprl_tpu.utils.host import HostParamMirror
from sheeprl_tpu.replay import make_replay_buffer
from sheeprl_tpu.data.staging import make_replay_staging
from sheeprl_tpu.envs.rollout import BurstActor
from sheeprl_tpu.envs.vector import make_vector_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.obs import (
    learn_probes,
    log_sps_metrics,
    observe_probes,
    probes_enabled,
    profile_tick,
    span,
)
from sheeprl_tpu.obs.dist import pmean
from sheeprl_tpu.utils.optim import clip_norm_of
from sheeprl_tpu.utils.utils import fetch_losses_if_observed, save_configs
from sheeprl_tpu.utils.jax_compat import shard_map


def build_train_fn(
    actor: SACActor,
    critic: DROQCritic,
    actor_tx,
    qf_tx,
    alpha_tx,
    cfg,
    fabric,
    action_scale: np.ndarray,
    action_bias: np.ndarray,
    target_entropy: float,
):
    """G dropout-critic steps + one actor/alpha step, compiled as one SPMD
    program. ``critic_batch`` leaves are ``[G, B_local, ...]``;
    ``actor_batch`` leaves are ``[B_local, ...]``."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    n_critics = int(cfg.algo.critic.n)
    axis = fabric.data_axis
    scale = jnp.asarray(action_scale)
    bias = jnp.asarray(action_bias)
    tgt_entropy = jnp.float32(target_entropy)
    # learning-health probes (obs/learn): build-time gate, zero ops when off
    learn_on = probes_enabled(cfg)
    learn_clips = {
        "actor": clip_norm_of(actor_tx),
        "critic": clip_norm_of(qf_tx),
        "alpha": clip_norm_of(alpha_tx),
    }

    def critic_step(carry, batch_and_key):
        state, qf_opt = carry
        batch, key = batch_and_key
        next_key, tgt_key, drop_key = jax.random.split(key, 3)

        alpha = jax.lax.stop_gradient(jnp.exp(state["log_alpha"]))
        next_mean, next_std = actor.apply({"params": state["actor"]}, batch["next_observations"])
        next_actions, next_logprob = squash_sample(next_mean, next_std, next_key, scale, bias)
        target_q = droq_ensemble_q(
            critic, state["target_critics"], batch["next_observations"], next_actions, tgt_key
        )
        min_target = jnp.min(target_q, axis=-1, keepdims=True) - alpha * next_logprob
        td_target = jax.lax.stop_gradient(
            batch["rewards"] + (1.0 - batch["dones"]) * gamma * min_target
        )

        def qf_loss_fn(critic_params):
            q = droq_ensemble_q(critic, critic_params, batch["observations"], batch["actions"], drop_key)
            # per-member MSE against the shared target (Algorithm 2, line 8)
            return sum(((q[..., i : i + 1] - td_target) ** 2).mean() for i in range(n_critics))

        qf_loss, qf_grads = jax.value_and_grad(qf_loss_fn)(state["critics"])
        qf_grads = pmean(qf_grads, axis)
        qf_updates, qf_opt = qf_tx.update(qf_grads, qf_opt, state["critics"])
        critics = optax.apply_updates(state["critics"], qf_updates)
        targets = jax.tree_util.tree_map(
            lambda p, t: tau * p + (1.0 - tau) * t, critics, state["target_critics"]
        )
        new_state = {**state, "critics": critics, "target_critics": targets}
        if learn_on:
            probes = learn_probes(
                {"critic": qf_grads},
                params={"critic": state["critics"]},
                updates={"critic": qf_updates},
                losses=qf_loss,
                clip_norms=learn_clips,
            )
            return (new_state, qf_opt), (qf_loss, probes)
        return (new_state, qf_opt), qf_loss

    def local_train(state, opt_states, critic_batch, actor_batch, key):
        g = jax.tree_util.tree_leaves(critic_batch)[0].shape[0]
        keys = jax.random.split(key, g + 2)
        (state, qf_opt), qf_ys = jax.lax.scan(
            critic_step, (state, opt_states["qf"]), (critic_batch, keys[:g])
        )
        qf_losses, critic_probes = qf_ys if learn_on else (qf_ys, None)

        # ---- actor update from the separate batch, mean over the ensemble
        alpha = jax.lax.stop_gradient(jnp.exp(state["log_alpha"]))

        def actor_loss_fn(actor_params):
            mean, std = actor.apply({"params": actor_params}, actor_batch["observations"])
            actions, logprob = squash_sample(mean, std, keys[g], scale, bias)
            q = droq_ensemble_q(critic, state["critics"], actor_batch["observations"], actions, keys[g + 1])
            mean_q = jnp.mean(q, axis=-1, keepdims=True)
            return policy_loss(alpha, logprob, mean_q), logprob

        (actor_loss, logprob), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            state["actor"]
        )
        actor_grads = pmean(actor_grads, axis)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states["actor"], state["actor"])
        actor_params = optax.apply_updates(state["actor"], actor_updates)

        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, jax.lax.stop_gradient(logprob), tgt_entropy)

        alpha_loss, alpha_grad = jax.value_and_grad(alpha_loss_fn)(state["log_alpha"])
        alpha_grad = pmean(alpha_grad, axis)
        alpha_updates, alpha_opt = alpha_tx.update(alpha_grad, opt_states["alpha"], state["log_alpha"])
        log_alpha = optax.apply_updates(state["log_alpha"], alpha_updates)

        new_state = {**state, "actor": actor_params, "log_alpha": log_alpha}
        opt_states = {"actor": actor_opt, "qf": qf_opt, "alpha": alpha_opt}
        metrics = pmean(
            jnp.stack([jnp.mean(qf_losses), actor_loss, alpha_loss]), axis
        )
        if learn_on:
            actor_probes = learn_probes(
                {"actor": actor_grads, "alpha": alpha_grad},
                params={"actor": state["actor"], "alpha": state["log_alpha"]},
                updates={"actor": actor_updates, "alpha": alpha_updates},
                losses=(actor_loss, alpha_loss),
                clip_norms=learn_clips,
            )
            # the critic scan yields [G]-stacked samples, the actor/alpha
            # update one more — concatenate per key (the sentinel ravels)
            probes = {}
            for d in (critic_probes, actor_probes):
                for k, v in d.items():
                    v = jnp.ravel(v)
                    probes[k] = (
                        v if k not in probes else jnp.concatenate([probes[k], v])
                    )
            return new_state, opt_states, metrics, probes
        return new_state, opt_states, metrics

    shmapped = shard_map(
        local_train,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(None, axis), P(axis), P()),
        out_specs=(P(), P(), P()) + ((P(),) if learn_on else ()),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if "minedojo" in (cfg.env.wrapper._target_ or "").lower():
        raise ValueError("MineDojo is not currently supported by DroQ agent")

    world_size = fabric.world_size
    root_key = fabric.seed_everything(cfg.seed)

    if len(cfg.cnn_keys.encoder) > 0:
        warnings.warn("DroQ algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.cnn_keys.encoder = []

    state = None
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    n_envs = int(cfg.env.num_envs) * world_size
    # vector backend picked by env.vectorization (envs/vector/factory.py)
    envs = make_vector_env(cfg, fabric, log_dir)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the DroQ agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the DroQ agent. "
                f"Provided environment: {cfg.env.id}"
            )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder MLP keys:", cfg.mlp_keys.encoder)

    act_dim = int(np.prod(action_space.shape))
    obs_dim = int(sum(np.prod(observation_space[k].shape) for k in cfg.mlp_keys.encoder))
    action_scale, action_bias = action_bounds(action_space)

    actor = SACActor(action_dim=act_dim, hidden_size=cfg.algo.actor.hidden_size)
    critic = DROQCritic(
        hidden_size=cfg.algo.critic.hidden_size, num_critics=1, dropout=cfg.algo.critic.dropout
    )
    target_entropy = -float(act_dim)

    root_key, a_key, c_key = jax.random.split(root_key, 3)
    actor_params = actor.init(a_key, jnp.zeros((1, obs_dim), jnp.float32))["params"]
    critic_params = init_droq_ensemble(critic, c_key, int(cfg.algo.critic.n), obs_dim, act_dim)
    agent_state = {
        "actor": actor_params,
        "critics": critic_params,
        "target_critics": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], jnp.float32)),
    }

    qf_tx = instantiate(cfg.algo.critic.optimizer)
    actor_tx = instantiate(cfg.algo.actor.optimizer)
    alpha_tx = instantiate(cfg.algo.alpha.optimizer)
    opt_states = {
        "actor": actor_tx.init(agent_state["actor"]),
        "qf": qf_tx.init(agent_state["critics"]),
        "alpha": alpha_tx.init(agent_state["log_alpha"]),
    }

    if cfg.checkpoint.resume_from:
        template = {
            "agent": agent_state,
            "opt_states": opt_states,
            "update": 0,
            "batch_size": 0,
            "last_log": 0,
            "last_checkpoint": 0,
        }
        state = fabric.load(cfg.checkpoint.resume_from, template)
        agent_state = state["agent"]
        opt_states = state["opt_states"]
        cfg.per_rank_batch_size = int(np.asarray(state["batch_size"])) // world_size
    agent_state = jax.device_put(agent_state, fabric.replicated)
    opt_states = jax.device_put(opt_states, fabric.replicated)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)

    rb = make_replay_buffer(
        cfg,
        fabric,
        log_dir,
        n_envs=n_envs,
        obs_keys=("observations",),
        dry_run_size=1,
    )

    scale_j, bias_j = jnp.asarray(action_scale), jnp.asarray(action_bias)

    actor_mirror = HostParamMirror.from_cfg(agent_state["actor"], fabric, cfg)
    play_actor = actor_mirror(agent_state["actor"])

    train_fn = build_train_fn(
        actor, critic, actor_tx, qf_tx, alpha_tx, cfg, fabric, action_scale, action_bias, target_entropy
    )
    critic_sharding = fabric.sharding(None, fabric.data_axis)
    # TPU-first replay staging (data/staging.py): device-ring gathers when
    # buffer.device_ring=True, double-buffered host prefetch otherwise; the
    # actor batch is the [0] slice of a [1, B, ...] burst, so both batches
    # flow through the same facade (its burst sharding matches
    # critic_sharding, and slicing yields the actor's fabric.data_sharding)
    staging = make_replay_staging(
        cfg, fabric, rb, batch_sharding=critic_sharding, seed=cfg.seed
    )
    rb = staging.rb

    last_train = 0
    train_step = 0
    start_step = int(np.asarray(state["update"])) // world_size if state is not None else 1
    policy_step = int(np.asarray(state["update"])) * cfg.env.num_envs if state is not None else 0
    last_log = int(np.asarray(state["last_log"])) if state is not None else 0
    last_checkpoint = int(np.asarray(state["last_checkpoint"])) if state is not None else 0
    policy_steps_per_update = int(n_envs)
    warn_checkpoint_rounding(cfg, policy_steps_per_update)
    num_updates = int(cfg.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    if cfg.checkpoint.resume_from and not cfg.buffer.get("checkpoint", False):
        learning_starts += start_step

    o = envs.reset(seed=cfg.seed)[0]
    obs = concat_obs(o, cfg.mlp_keys.encoder, n_envs)
    per_rank_gradient_steps = int(cfg.algo.per_rank_gradient_steps)
    root_key, play_key = jax.random.split(root_key)
    play_key = actor_mirror.put_key(play_key)
    # burst acting (envs/rollout, howto/rollout_engine.md): K env steps per
    # device dispatch; 1 (the default) reproduces the per-step path exactly
    act_burst = max(int(cfg.env.get("act_burst", 1) or 1), 1)

    # The acting loop body as one host function — env step, SAME_STEP
    # final_obs fixup, episode logging, buffer add: the old per-step block
    # verbatim. The BurstActor scans it K times per dispatch through an
    # ordered io_callback; the random prefill calls it directly.
    state_box = {"obs": obs, "policy_step": policy_step}

    def _host_env_step(actions):
        actions = np.asarray(actions)
        state_box["policy_step"] += n_envs
        with span("Time/env_interaction_time", SumMetric(sync_on_compute=False), phase="env"):
            next_o, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
        dones = np.logical_or(terminated, truncated)

        if cfg.metric.log_level > 0 and "final_info" in infos:
            fi = infos["final_info"]
            if isinstance(fi, dict) and "episode" in fi:
                mask = np.asarray(fi.get("_episode", []), dtype=bool)
                for i in np.nonzero(mask)[0]:
                    ep_rew = float(fi["episode"]["r"][i])
                    ep_len = float(fi["episode"]["l"][i])
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                        aggregator.update("Game/ep_len_avg", ep_len)
                    fabric.print(
                        f"Rank-0: policy_step={state_box['policy_step']}, reward_env_{i}={ep_rew}"
                    )

        next_obs = concat_obs(next_o, cfg.mlp_keys.encoder, n_envs)
        real_next_obs = next_obs.copy()
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    real_next_obs[idx] = concat_obs(final_obs, cfg.mlp_keys.encoder, 1)[0]

        step_data = {
            "observations": state_box["obs"][None],
            "actions": np.asarray(actions, np.float32).reshape(1, n_envs, -1),
            "rewards": np.asarray(rewards, np.float32).reshape(1, n_envs, 1),
            "dones": np.asarray(dones, np.float32).reshape(1, n_envs, 1),
        }
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = real_next_obs[None]
        rb.add(step_data)
        state_box["obs"] = next_obs
        return next_obs

    def _act_fn(actor_params, a_obs, key):
        # key advances inside the jitted burst: same discipline as the old
        # per-step policy_fn, so K=1 is bitwise the per-step path
        key, sub = jax.random.split(key)
        mean, std = actor.apply({"params": actor_params}, a_obs)
        actions, _ = squash_sample(mean, std, sub, scale_j, bias_j)
        return (actions,), key

    burst_actor = BurstActor(_act_fn, _host_env_step, obs)

    update = start_step
    while update <= num_updates:
        if update <= learning_starts:
            n_act = 1
            _host_env_step(envs.action_space.sample())
        else:
            n_act = max(min(act_burst, num_updates - update + 1), 1)
            with span("Time/rollout_time", SumMetric(sync_on_compute=False), phase="rollout"):
                _, play_key = burst_actor.rollout(
                    play_actor, state_box["obs"], play_key, n_act
                )
        policy_step = state_box["policy_step"]
        first = update
        update += n_act
        last = update - 1

        # one train round per update index the burst covered (K=1 reduces to
        # the reference per-update cadence; the per-update actor batch and
        # target-EMA semantics stay exact for every K)
        for u in range(first, last + 1):
            if u <= learning_starts:
                continue
            # both bursts arrive as device arrays: ring-gathered from HBM, or
            # host-sampled + device_put overlapped with the previous burst
            critic_batch = staging.sample_device(
                world_size * cfg.per_rank_batch_size,
                n_samples=per_rank_gradient_steps,
                sample_next_obs=cfg.buffer.sample_next_obs,
            )
            actor_batch = {
                k: v[0]
                for k, v in staging.sample_device(
                    world_size * cfg.per_rank_batch_size
                ).items()
            }

            with span("Time/train_time", SumMetric(sync_on_compute=cfg.metric.sync_on_compute), phase="train"):
                root_key, train_key = jax.random.split(root_key)
                outs = train_fn(
                    agent_state, opt_states, critic_batch, actor_batch, train_key
                )
                agent_state, opt_states, losses = outs[0], outs[1], outs[2]
                observe_probes(outs[3] if len(outs) > 3 else None, step=policy_step)
                losses = fetch_losses_if_observed(losses, aggregator)
                play_actor = actor_mirror(agent_state["actor"])
            train_step += world_size

            if aggregator and not aggregator.disabled:
                aggregator.update("Loss/value_loss", losses[0])
                aggregator.update("Loss/policy_loss", losses[1])
                aggregator.update("Loss/alpha_loss", losses[2])

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or last == num_updates
        ):
            if aggregator and not aggregator.disabled:
                metrics_dict = aggregator.compute()
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                aggregator.reset()
            log_sps_metrics(
                logger,
                policy_step=policy_step,
                last_log=last_log,
                train_step=train_step,
                last_train=last_train,
                world_size=world_size,
                action_repeat=cfg.env.action_repeat,
            )
            profile_tick(policy_step=policy_step, world_size=world_size)
            last_log = policy_step
            last_train = train_step

        if should_checkpoint(cfg, policy_step, last_checkpoint, last, num_updates):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(agent_state),
                "opt_states": jax.device_get(opt_states),
                "update": last * world_size,
                "batch_size": cfg.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{fabric.global_rank}")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.get("checkpoint", False) else None,
            )
            if preemption_requested():
                # SIGTERM/SIGINT: the final checkpoint is saved (the CLI
                # drains the in-flight write) — leave the train loop cleanly
                break

    staging.close()
    envs.close()
    if fabric.is_global_zero and cfg.algo.get("run_test", True) and not preemption_requested():
        test(actor, agent_state["actor"], scale_j, bias_j, fabric, cfg, log_dir)
