"""DroQ evaluation entrypoint (reference ``sheeprl/algos/droq/evaluate.py``):
the actor is a plain SAC actor, so the SAC eval-policy builder (registered
for ``droq`` in ``algos/sac/evaluate.py``) serves it through the shared
service."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.evals.service import run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["droq"])
def evaluate_droq(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
