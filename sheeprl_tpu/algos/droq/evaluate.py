"""DroQ evaluation entrypoint (reference ``sheeprl/algos/droq/evaluate.py``):
the actor is a plain SAC actor, so evaluation is SAC's greedy test."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.sac.evaluate import evaluate_sac
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["droq"])
def evaluate_droq(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    evaluate_sac(fabric, cfg, state)
