"""DroQ helpers (reference ``sheeprl/algos/droq`` reuses SAC's)."""

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, concat_obs, test  # noqa: F401