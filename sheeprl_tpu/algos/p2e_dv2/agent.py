"""Plan2Explore-DV2 agent (reference ``sheeprl/algos/p2e_dv2/agent.py``
build_agent :33-214 and the ensemble construction in
``p2e_dv2_exploration.py:560-600``).

DV2 chassis + the P2E additions: a vmapped next-state ensemble (predicting
the flat posterior), a dual actor, and an exploration critic with its own
hard-copied target. See ``p2e_dv3/agent.py`` for the stacked-ensemble
design notes.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import (
    Actor,
    MLPHead,
    WorldModel,
    build_player_fns,  # noqa: F401
    xavier_normal_initialization,
)
from sheeprl_tpu.algos.p2e_dv3.agent import (  # noqa: F401
    EnsembleMember,
    apply_ensemble,
    init_ensemble,
)


def build_agent(
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    observation_space,
    key: jax.Array,
) -> Tuple[WorldModel, Actor, MLPHead, EnsembleMember, Dict[str, Any]]:
    """Returns ``(world_model, actor, critic, ensemble_member, params)`` with
    ``params = {world_model, actor_task, critic_task, target_critic_task,
    actor_exploration, critic_exploration, target_critic_exploration,
    ensembles}``."""
    from sheeprl_tpu.algos.dreamer_v2.agent import build_agent as dv2_build_agent

    k_dv2, k_expl_actor, k_expl_critic, k_ens, k_xa, k_xc = jax.random.split(key, 6)
    world_model, actor, critic, dv2_params = dv2_build_agent(
        cfg, actions_dim, is_continuous, observation_space, k_dv2
    )
    wm_cfg = cfg.algo.world_model
    stoch_flat = int(wm_cfg.stochastic_size) * int(wm_cfg.discrete_size)
    rec_size = int(wm_cfg.recurrent_model.recurrent_state_size)
    latent_size = stoch_flat + rec_size
    act_dim = int(np.sum(actions_dim))

    actor_expl_params = xavier_normal_initialization(
        actor.init(k_expl_actor, jnp.zeros((1, latent_size)))["params"], k_xa
    )
    critic_expl_params = xavier_normal_initialization(
        critic.init(k_expl_critic, jnp.zeros((1, latent_size)))["params"], k_xc
    )

    ens_cfg = cfg.algo.ensembles
    ensemble_member = EnsembleMember(
        output_dim=stoch_flat,
        mlp_layers=int(ens_cfg.mlp_layers),
        dense_units=int(ens_cfg.dense_units),
        layer_norm=bool(ens_cfg.get("layer_norm", False)),
        activation=ens_cfg.dense_act,
    )
    ensembles = init_ensemble(ensemble_member, int(ens_cfg.n), latent_size + act_dim, k_ens)

    params = {
        "world_model": dv2_params["world_model"],
        "actor_task": dv2_params["actor"],
        "critic_task": dv2_params["critic"],
        "target_critic_task": dv2_params["target_critic"],
        "actor_exploration": actor_expl_params,
        "critic_exploration": critic_expl_params,
        "target_critic_exploration": jax.tree_util.tree_map(jnp.copy, critic_expl_params),
        "ensembles": ensembles,
    }
    return world_model, actor, critic, ensemble_member, params
