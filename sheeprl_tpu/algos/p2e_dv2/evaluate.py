"""P2E-DV2 evaluation (reference ``sheeprl/algos/p2e_dv2/evaluate.py``):
registered for both phases; always evaluates the **task** actor, through the
shared eval service."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax

from sheeprl_tpu.algos.dreamer_v2.utils import normalize_obs_jnp
from sheeprl_tpu.algos.p2e_dv2.agent import build_agent, build_player_fns
from sheeprl_tpu.evals.builders import actions_dim_of, dreamer_eval_policy
from sheeprl_tpu.evals.service import EvalPolicy, register_eval_builder, run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_eval_builder(algorithms=["p2e_dv2_exploration", "p2e_dv2_finetuning"])
def p2e_dv2_eval_policy(fabric, cfg, state, observation_space, action_space) -> EvalPolicy:
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    actions_dim, is_continuous = actions_dim_of(action_space)
    world_model, actor, _, _, _ = build_agent(
        cfg, actions_dim, is_continuous, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    params = params_on_device(state["agent"]["params"])
    # exploration checkpoints carry actor_task; finetuning checkpoints carry actor
    actor_params = params.get("actor_task", params.get("actor"))
    player_fns = build_player_fns(world_model, actor, cfg, actions_dim, is_continuous)
    return dreamer_eval_policy(
        player_fns,
        {"world_model": params["world_model"], "actor": actor_params},
        cfg,
        is_continuous,
        normalize_fn=normalize_obs_jnp,
    )


@register_evaluation(algorithms=["p2e_dv2_exploration", "p2e_dv2_finetuning"])
def evaluate_p2e_dv2(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
