"""P2E-DV2 utilities (reference ``sheeprl/algos/p2e_dv2/utils.py``):
metric allow-list for both phases."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v2.utils import AGGREGATOR_KEYS as _DV2_KEYS

AGGREGATOR_KEYS = _DV2_KEYS | {
    "Loss/ensemble_loss",
    "Loss/policy_loss_exploration",
    "Loss/value_loss_exploration",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/ensemble",
    "Grads/actor_exploration",
    "Grads/critic_exploration",
    "Grads/actor_task",
    "Grads/critic_task",
}
