"""SAC-AE evaluation (reference ``sheeprl/algos/sac_ae/evaluate.py``),
collapsed onto the shared eval service: encoder + actor trunk rebuilt from
the run config, greedy tanh action on a batch."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import action_bounds
from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.algos.sac_ae.utils import normalize_obs_jnp, prepare_obs
from sheeprl_tpu.evals.service import EvalPolicy, register_eval_builder, run_eval_entrypoint
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_eval_builder(algorithms=["sac_ae"])
def sac_ae_eval_policy(fabric, cfg, state, observation_space, action_space) -> EvalPolicy:
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")

    act_dim = int(np.prod(action_space.shape))
    action_scale, action_bias = action_bounds(action_space)
    scale = jnp.asarray(action_scale)
    bias = jnp.asarray(action_bias)
    encoder, _, _, actor_trunk, _ = build_agent(
        cfg, act_dim, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    params = params_on_device(state["agent"])
    cnn_keys = list(cfg.cnn_keys.encoder)
    mlp_keys = list(cfg.mlp_keys.encoder)

    @jax.jit
    def _act(p, obs):
        feat = encoder.apply({"params": p["encoder"]}, obs)
        mean, _ = actor_trunk.apply({"params": p["actor"]}, feat)
        return jnp.tanh(mean) * scale + bias

    def act(obs, policy_state, key):
        n = int(np.asarray(next(iter(obs.values()))).shape[0])
        prepared = prepare_obs(obs, cnn_keys, mlp_keys, n)
        norm = normalize_obs_jnp(prepared, cnn_keys)
        return np.asarray(_act(params, norm)), policy_state

    return EvalPolicy(act=act)


@register_evaluation(algorithms=["sac_ae"])
def evaluate_sac_ae(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    run_eval_entrypoint(fabric, cfg, state)
