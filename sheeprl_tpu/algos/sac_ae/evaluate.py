"""SAC-AE evaluation entrypoint (reference ``sheeprl/algos/sac_ae/evaluate.py``)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import action_bounds
from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.algos.sac_ae.utils import test
from sheeprl_tpu.envs.vector import make_eval_env
from sheeprl_tpu.utils.logger import create_tensorboard_logger
from sheeprl_tpu.utils.registry import register_evaluation
from sheeprl_tpu.utils.utils import params_on_device


@register_evaluation(algorithms=["sac_ae"])
def evaluate_sac_ae(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger, log_dir = create_tensorboard_logger(cfg)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))

    env = make_eval_env(cfg, log_dir)
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    act_dim = int(np.prod(action_space.shape))
    action_scale, action_bias = action_bounds(action_space)
    env.close()

    encoder, decoder, qf, actor_trunk, _ = build_agent(
        cfg, act_dim, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    params = params_on_device(state["agent"])
    test(
        encoder, actor_trunk, params,
        jnp.asarray(action_scale), jnp.asarray(action_bias),
        fabric, cfg, log_dir,
    )
